"""The serving daemon: ONE persistent engine, many concurrent tenants.

``ServeDaemon`` composes the repo's existing parts into a resident
server (ROADMAP open item #2):

- a single long-lived execution engine (default ``"jax"``) entered as a
  context for the daemon's whole lifetime, so per-run context push/pop
  from concurrent job threads never tears it down between requests;
- :class:`~fugue_tpu.serve.session.SessionManager` sessions whose saved
  tables live device-resident in the SQL engine's catalog under a
  per-session namespace (hot across requests, no re-ingest) and are
  claimed as the memory governor's *tenants* for fair-spill accounting;
- :class:`~fugue_tpu.serve.scheduler.JobScheduler` running up to
  ``fugue.serve.max_concurrent`` FugueSQL workflows concurrently against
  the shared engine with the workflow runner's timeout + cancellation
  machinery;
- :class:`~fugue_tpu.serve.http.ServeHTTPServer` exposing the JSON API
  below on the hardened HTTP layer.

Resilience plane (ISSUE 7):

- **durable state** — with ``fugue.serve.state_path`` set, sessions,
  hot-table fingerprints and async jobs journal through
  :class:`~fugue_tpu.serve.state.ServeStateJournal`; a restarted daemon
  rehydrates sessions, lazily reloads integrity-verified hot tables and
  resubmits interrupted async jobs under their original ids;
- **graceful drain** — ``stop(drain=True)`` (or SIGTERM via
  :meth:`install_signal_handlers`) flips healthy→draining: new
  submissions answer 503 + ``Retry-After`` while in-flight jobs run to
  the ``fugue.serve.drain_timeout`` deadline, then state is journaled
  and the engine context closes;
- **backpressure** — queue-depth (``fugue.serve.max_queue``),
  memory-pressure (``fugue.serve.memory_reject_fraction`` over the HBM
  ledger) and per-session caps (``fugue.serve.session_max_jobs``)
  answer 503/429 + ``Retry-After``; deep-queue sync submits degrade to
  async 202 + job-id (``fugue.serve.sync_degrade_depth``);
- **supervision** — per-job heartbeats with a wedged-job watchdog, and
  consecutive-failure circuit breakers per session and per query
  fingerprint (deterministic workflow uuid) that quarantine poison
  queries with a structured error.

HTTP API (all JSON; errors are structured payloads, never tracebacks)::

    POST   /v1/sessions                     {"ttl": seconds?}
    GET    /v1/sessions
    GET    /v1/sessions/<sid>
    POST   /v1/sessions/<sid>/close         (alias: DELETE /v1/sessions/<sid>)
    POST   /v1/sessions/<sid>/sql           {"sql": ..., "save_as"?: name,
                                             "mode"?: "sync"|"async",
                                             "timeout"?: s, "collect"?: bool,
                                             "limit"?: rows,
                                             "profile"?: bool (EXPLAIN
                                             ANALYZE via /profile),
                                             "explain"?: bool (static plan
                                             report, nothing executes)}
    GET    /v1/jobs/<jid>                   poll an async submission
    GET    /v1/jobs/<jid>/profile           per-task runtime profile
    POST   /v1/jobs/<jid>/cancel
    GET    /v1/status                       health, memory_stats, breakers,
                                            backpressure, recovery, jobs,
                                            uptime_secs, version,
                                            compile_cache
    GET    /v1/health                       200 healthy / 503 draining
    GET    /v1/metrics                      Prometheus text exposition

Observability plane (ISSUE 8): every route accepts/echoes
``X-Request-Id`` (generated when absent/unsafe) and, with
``fugue.obs.enabled``, runs under a request trace whose spans follow the
job through the workflow into engine compile/execute/transfer — exported
as Perfetto-loadable Chrome-trace JSON under ``fugue.obs.trace_path``;
jobs over ``fugue.obs.slow_query_ms`` log a structured span breakdown.
"""

import re
import signal
import threading
import time
import uuid
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Tuple

from fugue_tpu.constants import (
    FUGUE_CONF_SERVE_ADMISSION_DEFAULT_BYTES,
    FUGUE_CONF_SERVE_ADMISSION_DEFAULT_MS,
    FUGUE_CONF_SERVE_ADMISSION_MAX_WAIT,
    FUGUE_CONF_SERVE_ADMISSION_MEMORY_FRACTION,
    FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR,
    FUGUE_CONF_SERVE_PREWARM,
    FUGUE_CONF_SERVE_RESULT_CACHE,
    FUGUE_CONF_SERVE_SCHEDULER,
    FUGUE_CONF_SERVE_BREAKER_COOLDOWN,
    FUGUE_CONF_SERVE_BREAKER_THRESHOLD,
    FUGUE_CONF_SERVE_DRAIN_TIMEOUT,
    FUGUE_CONF_SERVE_HEARTBEAT_TIMEOUT,
    FUGUE_CONF_SERVE_HOST,
    FUGUE_CONF_SERVE_JOB_TTL,
    FUGUE_CONF_SERVE_MAX_CONCURRENT,
    FUGUE_CONF_SERVE_MAX_QUEUE,
    FUGUE_CONF_SERVE_MEMORY_REJECT,
    FUGUE_CONF_SERVE_PORT,
    FUGUE_CONF_SERVE_SESSION_MAX_JOBS,
    FUGUE_CONF_SERVE_SESSION_TTL,
    FUGUE_CONF_SERVE_STATE_PATH,
    FUGUE_CONF_SERVE_SYNC_DEGRADE_DEPTH,
    FUGUE_CONF_SERVE_SYNC_WAIT,
    FUGUE_CONF_STATS_HISTORY,
    FUGUE_CONF_STATS_PATH,
    typed_conf_get,
)
from fugue_tpu.execution.factory import make_execution_engine
from fugue_tpu.obs import (
    activate,
    current_span,
    finalize_trace,
    force_profiling,
    maybe_log_slow_query,
    obs_options,
    open_trace,
    start_span,
    suppress_tracing,
)
from fugue_tpu.rpc.http import structured_error
from fugue_tpu.serve.http import ServeHTTPServer
from fugue_tpu.serve.scheduler import (
    CANCELLED,
    ERROR,
    JobScheduler,
    ServeJob,
)
from fugue_tpu.serve.session import ServeSession, SessionManager
from fugue_tpu.serve.state import ServeStateJournal, make_journal
from fugue_tpu.serve.supervisor import (
    AdmissionError,
    BackpressureError,
    EngineSupervisor,
    HealthState,
    SessionBusyError,
    STOPPED,
)
from fugue_tpu.sql_frontend.workflow_sql import FugueSQLWorkflow
from fugue_tpu.testing.faults import fault_point
from fugue_tpu.testing.locktrace import (
    active_sanitizer,
    disable_lock_sanitizer,
    maybe_enable_from_conf,
    tracked_lock,
)
from fugue_tpu.testing.retrace import (
    active_retrace_sentinel,
    disable_retrace_sentinel,
)
from fugue_tpu.testing.retrace import (
    maybe_enable_from_conf as retrace_enable_from_conf,
)
from fugue_tpu.utils.params import ParamDict

_RESULT_YIELD = "serve_result"

# breaker accounting must not count a breaker's own rejections as fresh
# failures (that would extend a quarantine every time someone probes it)
_BREAKER_ERRORS = ("PoisonQueryError", "CircuitOpenError")

# X-Request-Id hygiene: the inbound header becomes a trace id (and so a
# trace FILENAME under fugue.obs.trace_path) — restrict it to a safe
# charset and length; anything else is replaced by a generated id
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

_REJECT_KINDS = (
    "draining",
    "queue_full",
    "memory_pressure",
    "session_cap",
    "breaker_open",
    "sync_degraded",
    "shed",
)
_FAULT_KINDS = (
    "runs",
    "retries",
    "recoveries",
    "degradations",
    "integrity_rejected",
    "resumed",
)


def clean_request_id(raw: Optional[str]) -> Optional[str]:
    """The inbound ``X-Request-Id`` if it is safe to echo/journal/use as
    a trace id; None (→ generate one) otherwise."""
    if raw is None:
        return None
    rid = str(raw).strip()
    return rid if _REQUEST_ID_RE.match(rid) else None


def new_request_id() -> str:
    return "req-" + uuid.uuid4().hex[:16]


class ServeDaemon:
    """A long-lived in-process serving daemon. Usable as a context
    manager; ``start()`` binds the HTTP API and returns the daemon."""

    def __init__(self, conf: Any = None, engine: Any = "jax"):
        # debug lock-order sanitizer: must arm BEFORE the engine/
        # scheduler/session locks below are constructed so they wrap.
        # Remember whether THIS daemon armed it — stop() disarms then,
        # so a later same-process daemon without the flag gets plain
        # locks again instead of reporting into a dead scope
        self._owns_sanitizer = (
            active_sanitizer() is None
            and maybe_enable_from_conf(ParamDict(conf)) is not None
        )
        # debug retrace sentinel: same arming parity — conf-armed BEFORE
        # the engine exists so the very first dispatch is watched, and
        # owned arming is disarmed on stop()/_hard_kill() so a later
        # same-process daemon without the flag runs unwatched instead of
        # reporting into this daemon's dead scope
        self._owns_retrace_sentinel = (
            active_retrace_sentinel() is None
            and retrace_enable_from_conf(ParamDict(conf)) is not None
        )
        self._engine = make_execution_engine(engine, ParamDict(conf))
        econf = self._engine.conf
        self._journal = make_journal(
            self._engine, typed_conf_get(econf, FUGUE_CONF_SERVE_STATE_PATH)
        )
        # runtime-statistics store (ISSUE 14): a journaled daemon
        # defaults fugue.stats.path to <state_path>/stats, so profiled
        # jobs persist per-task observations next to the journal (the
        # engine conf carries the key — the workflow layer's profiler
        # writes through the same shared store instance)
        if (
            self._journal is not None
            and not str(
                typed_conf_get(econf, FUGUE_CONF_STATS_PATH) or ""
            ).strip()
        ):
            econf[FUGUE_CONF_STATS_PATH] = self._engine.fs.join(
                self._journal.base_uri, "stats"
            )
        self._stats_store: Any = None
        stats_path = str(
            typed_conf_get(econf, FUGUE_CONF_STATS_PATH) or ""
        ).strip()
        if stats_path:
            from fugue_tpu.obs.stats_store import get_stats_store

            self._stats_store = get_stats_store(
                self._engine,
                stats_path,
                history=typed_conf_get(econf, FUGUE_CONF_STATS_HISTORY),
            )
        self._health = HealthState()
        self._supervisor = EngineSupervisor(
            typed_conf_get(econf, FUGUE_CONF_SERVE_BREAKER_THRESHOLD),
            typed_conf_get(econf, FUGUE_CONF_SERVE_BREAKER_COOLDOWN),
            heartbeat_timeout=typed_conf_get(
                econf, FUGUE_CONF_SERVE_HEARTBEAT_TIMEOUT
            ),
            log=self._engine.log,
        )
        self._sessions = SessionManager(
            self._engine,
            default_ttl=typed_conf_get(econf, FUGUE_CONF_SERVE_SESSION_TTL),
            journal=self._journal,
        )
        # predictive overload plane (ISSUE 18): under
        # fugue.serve.scheduler=predictive the scheduler plans against
        # stats-store cost predictions — shortest-job-first inside
        # per-tenant fairness, priority/deadline submission fields, and
        # a PREDICTED-memory admission gate replacing the observed-fill
        # rejection. fifo (default) keeps PR 6 behavior bit-for-bit.
        self._scheduler_policy = str(
            typed_conf_get(econf, FUGUE_CONF_SERVE_SCHEDULER) or "fifo"
        ).lower()
        self._admission: Any = None
        if self._scheduler_policy == "predictive":
            from fugue_tpu.serve.admission import make_admission

            self._admission = make_admission(
                self._stats_store,
                typed_conf_get(econf, FUGUE_CONF_SERVE_MAX_CONCURRENT),
                typed_conf_get(
                    econf, FUGUE_CONF_SERVE_ADMISSION_MEMORY_FRACTION
                ),
                typed_conf_get(econf, FUGUE_CONF_SERVE_ADMISSION_DEFAULT_MS),
                typed_conf_get(
                    econf, FUGUE_CONF_SERVE_ADMISSION_DEFAULT_BYTES
                ),
                budget_bytes_fn=self._memory_budget_bytes,
            )
        self._admission_max_wait = max(
            0.0,
            float(
                typed_conf_get(econf, FUGUE_CONF_SERVE_ADMISSION_MAX_WAIT)
            ),
        )
        self._scheduler = JobScheduler(
            self._execute_job,
            typed_conf_get(econf, FUGUE_CONF_SERVE_MAX_CONCURRENT),
            job_ttl=typed_conf_get(econf, FUGUE_CONF_SERVE_JOB_TTL),
            on_finish=self._job_finished,
            policy=self._scheduler_policy,
            admission=self._admission,
        )
        http_conf = ParamDict(econf)
        http_conf["fugue.rpc.http_server.host"] = typed_conf_get(
            econf, FUGUE_CONF_SERVE_HOST
        )
        http_conf["fugue.rpc.http_server.port"] = typed_conf_get(
            econf, FUGUE_CONF_SERVE_PORT
        )
        self._http = ServeHTTPServer(self, http_conf)
        self._sync_wait = typed_conf_get(econf, FUGUE_CONF_SERVE_SYNC_WAIT)
        self._drain_timeout = typed_conf_get(
            econf, FUGUE_CONF_SERVE_DRAIN_TIMEOUT
        )
        self._max_queue = typed_conf_get(econf, FUGUE_CONF_SERVE_MAX_QUEUE)
        self._session_max_jobs = typed_conf_get(
            econf, FUGUE_CONF_SERVE_SESSION_MAX_JOBS
        )
        self._memory_reject = typed_conf_get(
            econf, FUGUE_CONF_SERVE_MEMORY_REJECT
        )
        self._sync_degrade_depth = typed_conf_get(
            econf, FUGUE_CONF_SERVE_SYNC_DEGRADE_DEPTH
        )
        self._started = False
        self._started_at: Optional[float] = None
        self._recovery: Dict[str, int] = {
            "sessions": 0,
            "pipelines": 0,
            "jobs_resubmitted": 0,
            "jobs_failed_over": 0,
        }
        self._drain_result: Optional[Dict[str, int]] = None
        # ---- cold-start pre-warm (ISSUE 11) ------------------------------
        # with a persistent executable cache configured, start() loads
        # the engine's cached executables in the background and
        # /v1/health answers 503 state="warming" until done — so an LB
        # routes the first query only when its dispatch is compile-free.
        # Phase timings (journal-reload / cache-load) plus the FIRST
        # query's compile/dispatch split land in status()["recovery"].
        self._prewarm_on = bool(
            typed_conf_get(econf, FUGUE_CONF_SERVE_PREWARM)
        )
        self._warming = False
        self._prewarm_thread: Optional[threading.Thread] = None
        self._restart_phases: Dict[str, Any] = {}
        self._first_query: Optional[Dict[str, Any]] = None
        self._first_query_lock = tracked_lock(
            "serve.daemon.ServeDaemon._first_query_lock"
        )
        # ---- standing pipelines / materialized views (ISSUE 15) ----------
        # (session_id, name) -> MaterializedView. Registration journals
        # the SPEC into the session record; restart/adoption rebuilds
        # the objects and each pipeline's progress manifest restores
        # its exactly-once state. The lock only guards the dict —
        # stepping/refreshing never runs under it.
        self._views: Dict[Tuple[str, str], Any] = {}
        self._views_lock = tracked_lock(
            "serve.daemon.ServeDaemon._views_lock"
        )
        # ---- observability plane (ISSUE 8) -------------------------------
        # the daemon's counters live on the ENGINE's metrics registry
        # (one registry per daemon by construction), rendered at
        # GET /v1/metrics; the status() payload keeps its historical
        # dict shapes as views over the families. Children are
        # pre-touched so scrapes see the full label schema at zero.
        self._obs = obs_options(econf)
        metrics = self._engine.metrics
        self._m_reject = metrics.counter(
            "fugue_serve_rejections_total",
            "submissions shed by admission control, by reason",
            ["kind"],
        )
        for kind in _REJECT_KINDS:
            self._m_reject.labels(kind=kind)
        self._m_fault = metrics.counter(
            "fugue_serve_fault_events_total",
            "workflow fault-tolerance events aggregated over served jobs",
            ["kind"],
        )
        for kind in _FAULT_KINDS:
            self._m_fault.labels(kind=kind)
        self._m_requests = metrics.counter(
            "fugue_serve_requests_total",
            "HTTP API requests by route family and status",
            ["route", "status"],
        )
        self._m_request_secs = metrics.histogram(
            "fugue_serve_request_seconds",
            "HTTP API request latency by route family",
            ["route"],
        )
        self._m_job_secs = metrics.histogram(
            "fugue_serve_job_seconds",
            "job execution wall clock (start to terminal) by outcome",
            ["status"],
        )
        # cross-request result cache (ISSUE 10): a resubmitted PURE
        # query (same session, same table-catalog epoch, same DAG uuid)
        # answers from the process-wide plan cache with zero execution —
        # no Python planning, no device dispatch, no recompile
        from fugue_tpu.optimize import get_plan_cache

        self._plan_cache = get_plan_cache()
        self._result_cache_on = bool(
            typed_conf_get(econf, FUGUE_CONF_SERVE_RESULT_CACHE)
        )
        # fleet tier (ISSUE 13): an fs-backed result cache shared by
        # every replica, keyed by the DAG fingerprint + the session
        # tables' artifact sha256s — content-addressed, so a migrated
        # (or merely content-identical) session warm-starts on ANY
        # replica without re-executing
        self._fleet_result_dir = str(
            typed_conf_get(econf, FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR)
            or ""
        ).strip()
        if self._fleet_result_dir:
            try:
                self._engine.fs.makedirs(
                    self._fleet_result_dir, exist_ok=True
                )
            except Exception:
                self._engine.log.warning(
                    "fugue_tpu serve: fleet result-cache dir %s is not "
                    "writable; cross-replica result cache disabled",
                    self._fleet_result_dir,
                )
                self._fleet_result_dir = ""
        self._m_result_cache = metrics.counter(
            "fugue_serve_result_cache_total",
            "cross-request query result cache lookups by result",
            ["result"],
        )
        for kind in ("hit", "miss", "fs_hit", "fs_miss", "fs_store",
                     "fs_error"):
            self._m_result_cache.labels(result=kind)
        # registry counters are process-monotonic (Prometheus
        # semantics), but status()'s dict shapes are DAEMON-scoped like
        # the dicts they replaced: baseline a caller-owned engine's
        # prior counts so a fresh daemon starts its payload at zero
        self._reject_base = self._m_reject.as_int_dict()
        self._fault_base = self._m_fault.as_int_dict()
        metrics.add_collector(self._collect_serve_gauges)

    # ---- lifecycle -------------------------------------------------------
    @property
    def engine(self) -> Any:
        return self._engine

    @property
    def sessions(self) -> SessionManager:
        return self._sessions

    @property
    def scheduler(self) -> JobScheduler:
        return self._scheduler

    @property
    def supervisor(self) -> EngineSupervisor:
        return self._supervisor

    @property
    def journal(self) -> Any:
        return self._journal

    @property
    def health_state(self) -> str:
        return self._health.state

    @property
    def address(self) -> Tuple[str, int]:
        """(host, port) of the bound HTTP API (after ``start``)."""
        return self._http.address

    def start(self) -> "ServeDaemon":
        if self._started:
            return self
        # hold the engine for the daemon's lifetime: concurrent job runs
        # push/pop their own per-thread contexts on top and the count
        # never reaches zero, so the engine stays hot between requests.
        # retain (not as_context): the hold must release cleanly from a
        # drain thread or signal handler, and the daemon's engine must
        # never become the caller thread's ambient context engine
        self._engine.retain()
        # prewarm BEFORE the scheduler/recovery can run any job: the
        # once-per-(dir,sig) warm claim is taken synchronously on this
        # thread inside warm_executables, so a recovered job's
        # streamed-ingest first-batch hook can never win it and turn
        # the readiness gate into a no-op
        self._start_prewarm()
        self._scheduler.start()
        if self._journal is not None:
            t0 = time.monotonic()
            self._recover()
            self._restart_phases["journal_reload_secs"] = round(
                time.monotonic() - t0, 4
            )
        self._supervisor.tick_hooks = [
            self._sessions.sweep,
            self._scheduler.gc_payloads,
            self._sweep_views,
        ]
        if self._journal is not None:
            self._supervisor.tick_hooks.append(self._journal.maybe_flush)
        self._supervisor.start(
            self._scheduler.running_jobs, abandon=self._scheduler.abandon
        )
        self._http.start()
        self._started = True
        self._started_at = time.time()
        return self

    def _start_prewarm(self) -> None:
        """Kick the background executable pre-warm when the engine has a
        persistent cache configured: deserializing the cached programs
        overlaps the rest of startup, and /v1/health reports
        ``warming`` (503) until the warm lands, so
        ``restart_recovery.time_to_first_query`` is IO-bound, not
        compile-bound. A no-op for cache-less engines."""
        if not self._prewarm_on:
            return
        begin = getattr(self._engine, "try_begin_warm", None)
        # the claim is taken HERE, on the starting thread, before the
        # scheduler exists — a recovered job's ingest hook can only
        # find it already owned and stay out of the readiness gate
        work = begin() if begin is not None else None
        if work is None:
            return
        self._warming = True

        def _warm() -> None:
            t0 = time.monotonic()
            loaded = 0
            try:
                loaded = int(self._prewarm(work) or 0)
            except Exception as ex:  # warm is best-effort, never fatal
                self._engine.log.warning(
                    "fugue_tpu serve: executable pre-warm failed "
                    "(%s: %s); first queries will compile",
                    type(ex).__name__, ex,
                )
            finally:
                self._restart_phases["cache_load_secs"] = round(
                    time.monotonic() - t0, 4
                )
                self._restart_phases["prewarmed_executables"] = loaded
                self._warming = False

        # through the exec-cache spawner: its atexit join keeps an
        # interpreter exiting WITHOUT daemon.stop() from tearing down
        # XLA under a thread still mid-deserialize (C++ abort)
        from fugue_tpu.optimize.exec_cache import spawn_warm_thread

        self._prewarm_thread = spawn_warm_thread(_warm)

    def _prewarm(self, work: Any) -> int:
        """Run the already-claimed warm: load the engine-signature-
        matching disk-cache entries (the executables every journaled
        query fingerprint compiled before the restart persisted here).
        Split out so tests can gate it."""
        return int(work() or 0)

    @property
    def ready(self) -> bool:
        """Healthy AND past the executable pre-warm — what
        ``GET /v1/health`` keys its 200 on."""
        return self._health.healthy and not self._warming

    def _recover(self) -> None:
        """Rehydrate the prior daemon's journaled state: sessions come
        back (tables reload lazily on first access), interrupted async
        jobs resubmit under their original ids, and jobs whose session
        did not survive fail over with a structured error a poller can
        read."""
        data = self._journal.load()
        self._recovery["sessions"] = self._sessions.restore(
            data.get("sessions") or {}
        )
        # standing pipelines rebuild from their journaled specs; each
        # progress manifest restores the last committed micro-batch
        self._recovery["pipelines"] = self._restore_views(
            data.get("sessions") or {}
        )
        resubmitted, failed_over = self._resubmit_journaled_jobs(
            data.get("jobs") or {}, import_into_journal=False
        )
        self._recovery["jobs_resubmitted"] += resubmitted
        self._recovery["jobs_failed_over"] += failed_over

    def _resubmit_journaled_jobs(
        self, jobs: Dict[str, Dict[str, Any]], import_into_journal: bool
    ) -> Tuple[int, int]:
        """Resubmit interrupted journaled jobs under their ORIGINAL ids
        (idempotent: saves are overwrite-mode); jobs whose session did
        not survive fail over with a structured error a poller can read.
        ``import_into_journal`` (the fleet-adoption path) records each
        resubmitted job into THIS daemon's journal first — restart
        recovery skips that, its jobs are already journaled here.
        Returns (resubmitted, failed_over)."""
        resubmitted = failed_over = 0
        for jid, rec in sorted(jobs.items()):
            job = ServeJob(
                rec.get("session_id", ""),
                rec.get("sql", ""),
                save_as=rec.get("save_as"),
                timeout=float(rec.get("timeout", 0.0) or 0.0),
                collect=bool(rec.get("collect", True)),
                limit=int(rec.get("limit", 10_000)),
                job_id=jid,
                request_id=rec.get("request_id"),
                profile=bool(rec.get("profile", False)),
                priority=int(rec.get("priority", 0) or 0),
                deadline=float(rec.get("deadline", 0.0) or 0.0),
            )
            job.recovered = True
            if self._admission is not None:
                job.cost = self._admission.model.estimate_sql(job.sql)
            try:
                self._sessions.get(job.session_id)
                if import_into_journal:
                    self._journal.record_job(job)
                self._scheduler.submit(job)
                resubmitted += 1
            except AdmissionError as ex:
                # this daemon started draining mid-loop. Do NOT
                # terminalize the job ("session did not survive" would
                # be a lie) and do NOT abort the pass — the sessions
                # are already adopted here, so aborting would let the
                # router re-adopt the same source elsewhere and
                # double-own them. DEFER instead: the job record is
                # (or stays) in THIS journal, and the failover that
                # follows this daemon's drain migrates it onward with
                # the sessions it belongs to.
                if import_into_journal:
                    self._journal.record_job(job)
                self._engine.log.warning(
                    "fugue_tpu serve: job %s deferred during "
                    "recovery/adoption (%s); it rides the next "
                    "failover of this daemon's journal",
                    jid, ex,
                )
            except Exception as ex:
                job.error = structured_error(
                    KeyError(
                        f"session {job.session_id} did not survive the "
                        f"daemon restart ({type(ex).__name__}); the job "
                        "cannot be resumed"
                    )
                )
                job.finish(ERROR)
                self._scheduler.adopt(job)
                self._journal.finish_job(jid)
                failed_over += 1
        return resubmitted, failed_over

    def adopt_state(self, state_path: str) -> Dict[str, Any]:
        """Fleet failover/handoff hook (``POST /v1/admin/adopt``): adopt
        a dead or drained replica's journaled state. Its unexpired
        sessions rehydrate HERE under their original ids (hot tables
        reload lazily from the shared-fs artifacts after fingerprint
        verification — the adoption analog of restart recovery), its
        interrupted async jobs resubmit under their original job ids,
        and the source journal is atomically emptied so a restarted
        origin replica cannot double-own the moved sessions."""
        if self._journal is None:
            raise ValueError(
                "this daemon has no state journal "
                "(fugue.serve.state_path); it cannot adopt replica state"
            )
        if not self._health.healthy:
            raise BackpressureError(
                f"daemon is {self._health.state}; not adopting sessions",
                retry_after=1.0,
            )
        base = str(state_path or "").strip()
        if base == "" or base.rstrip("/") == self._journal.base_uri:
            raise ValueError(f"invalid adoption source {state_path!r}")
        fs = self._engine.fs
        # CAS fence (write_file_if_absent): exactly ONE of N racing
        # adopters proceeds past this line per journal; the losers get
        # AdoptionFencedError and back off without reading any state.
        # The fence clears with the journal in clear_state; on a raised
        # adoption it is released so a later failover can retry.
        ServeStateJournal.acquire_adoption_fence(
            fs, base, owner=self._journal.base_uri
        )
        try:
            return self._adopt_state_fenced(base, fs)
        except BaseException:
            ServeStateJournal.clear_adoption_fence(fs, base)
            raise

    def _adopt_state_fenced(
        self, base: str, fs: Any
    ) -> Dict[str, Any]:
        data = ServeStateJournal.read_state(fs, base, log=self._engine.log)
        adopted, expired = self._sessions.adopt(data["sessions"])
        # the adopted sessions' standing pipelines move with them: the
        # specs rode along in the imported records, and the progress
        # manifests (origin state dir, shared fs) resume exactly-once
        adopted_pipelines = self._restore_views(
            data["sessions"], only=set(adopted)
        )
        self._recovery["pipelines"] += adopted_pipelines
        resubmitted, failed_over = self._resubmit_journaled_jobs(
            data["jobs"], import_into_journal=True
        )
        source_cleared = True
        try:
            ServeStateJournal.clear_state(fs, base)
        except Exception as ex:
            source_cleared = False
            # the adoption stands; a not-cleared source is logged loudly
            # because a restarted origin replica would double-own
            self._engine.log.warning(
                "fugue_tpu serve: adopted state from %s but could not "
                "clear the source journal (%s: %s) — do not restart the "
                "origin replica against it",
                base, type(ex).__name__, ex,
            )
        self._recovery["jobs_resubmitted"] += resubmitted
        self._recovery["jobs_failed_over"] += failed_over
        adopted_stats = 0
        if self._stats_store is not None:
            # the origin's runtime statistics ride along with its
            # sessions: merge its <state>/stats rings into ours so the
            # adopted queries keep their observed-rows history
            try:
                adopted_stats = self._stats_store.adopt(
                    fs.join(base, "stats")
                )
            except Exception:  # pragma: no cover - stats are best-effort
                pass
        return {
            "sessions": adopted,
            "expired_sessions": expired,
            "pipelines": adopted_pipelines,
            "stats_fingerprints": adopted_stats,
            "jobs_resubmitted": resubmitted,
            "jobs_failed_over": failed_over,
            # False = the origin journal still holds the moved state:
            # the operator/fleet must clear it before restarting the
            # origin replica, or it double-owns the sessions
            "source_cleared": source_cleared,
        }

    def stop(self, drain: bool = False) -> None:
        """Stop serving. ``drain=False`` (default) keeps PR 6 semantics:
        HTTP down first, scheduler cancelled, sessions closed, engine
        context stopped. ``drain=True`` is the graceful path: the health
        state flips to *draining* (new submissions answer 503 +
        Retry-After while polling keeps working), in-flight jobs get
        ``fugue.serve.drain_timeout`` seconds to finish, stragglers are
        cancelled and abandoned, and the final state is journaled BEFORE
        the engine context closes."""
        if not self._started:
            return
        if drain:
            self._health.start_drain(self._drain_timeout)
            self._drain_result = self._scheduler.drain(self._drain_timeout)
        self._started = False
        self._join_prewarm()
        # a stopped daemon must not keep publishing gauges through a
        # caller-owned engine's registry (stale values, leaked refs)
        self._engine.metrics.remove_collector(self._collect_serve_gauges)
        self._stop_views()  # tickers off; progress manifests survive
        self._supervisor.stop()
        self._http.stop()
        self._scheduler.stop()
        if self._journal is not None:
            # journaled daemon: keep durable state for the next start;
            # write the final snapshot before the engine dies
            self._sessions.shutdown()
            self._journal.write()
        else:
            self._sessions.close_all()
        self._engine.release()
        self._health.transition(STOPPED)
        if self._owns_sanitizer:
            disable_lock_sanitizer()
            self._owns_sanitizer = False
        if self._owns_retrace_sentinel:
            disable_retrace_sentinel()
            self._owns_retrace_sentinel = False

    def _join_prewarm(self) -> None:
        """A stopping daemon must not leave the warm thread touching a
        released engine; bounded join (the thread is a daemon)."""
        t = self._prewarm_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful drain (``stop(drain=True)`` on a
        helper thread, so the signal handler returns immediately). Call
        from the main thread of a dedicated serve process; in-process
        embeddings (tests, benches) should call ``stop`` directly."""

        def _drain_on_signal(signum: int, frame: Any) -> None:
            threading.Thread(
                target=self.stop, kwargs={"drain": True}, daemon=True,
                name="fugue-serve-drain",
            ).start()

        signal.signal(signal.SIGTERM, _drain_on_signal)
        signal.signal(signal.SIGINT, _drain_on_signal)

    def _hard_kill(self) -> None:
        """Chaos/test hook: the closest an in-process harness gets to
        ``kill -9`` mid-flight. No drain, no final journal write (the
        journal is incrementally crash-durable by construction), workers
        killed via sentinels, catalog copies dropped (device state dies
        with the process), engine context closed."""
        if not self._started:
            return
        self._started = False
        self._join_prewarm()
        self._engine.metrics.remove_collector(self._collect_serve_gauges)
        self._stop_views()
        # scheduler FIRST: its first act is dropping the finish
        # observers, so a job completing while the rest of the teardown
        # runs can no longer clean its journal entry — a real kill -9
        # would not have run those callbacks either
        self._scheduler.kill()
        self._supervisor.stop()
        self._http.stop()
        self._sessions.shutdown()  # drops catalog copies, keeps journal
        self._engine.release()
        self._health.transition(STOPPED)
        # even the kill path disarms an owned sanitizer/sentinel: a
        # restarted in-process daemon must not report into this dead scope
        if self._owns_sanitizer:
            disable_lock_sanitizer()
            self._owns_sanitizer = False
        if self._owns_retrace_sentinel:
            disable_retrace_sentinel()
            self._owns_retrace_sentinel = False

    def __enter__(self) -> "ServeDaemon":
        return self.start()

    def __exit__(self, *args: Any) -> None:
        self.stop()

    # ---- operations (HTTP routes call these; tests/benches may too) ------
    def create_session(self, ttl: Optional[float] = None) -> ServeSession:
        self._reject_if_unhealthy()
        return self._sessions.create(ttl=ttl)

    def close_session(self, session_id: str) -> Dict[str, Any]:
        self._drop_session_views(session_id)
        dropped = self._sessions.close(session_id)
        return {"closed": session_id, "dropped_tables": dropped}

    # ---- standing pipelines / materialized views (ISSUE 15) --------------
    def register_pipeline(
        self, session_id: str, payload: Dict[str, Any], step: bool = True
    ) -> Dict[str, Any]:
        """Register a standing pipeline maintaining ``payload["name"]``
        as this session's continuously-refreshed materialized view. The
        spec is journaled into the session record (restart + adoption
        rebuild it); the progress manifest defaults under the durable
        state path so a rebuilt pipeline resumes exactly-once. An
        initial ``step`` folds any already-arrived files so the view is
        queryable immediately."""
        from fugue_tpu.stream.pipeline import PipelineSpec
        from fugue_tpu.stream.view import MaterializedView, view_progress_uri

        self._reject_if_unhealthy()
        session = self._sessions.get(session_id)
        spec = PipelineSpec.from_dict(payload)
        if spec.progress is None and self._journal is not None:
            spec.progress = view_progress_uri(
                self._engine.fs,
                self._journal.base_uri,
                session_id,
                spec.name,
            )
        key = (session_id, spec.name)
        with self._views_lock:
            if key in self._views:
                raise ValueError(
                    f"pipeline {spec.name!r} is already registered on "
                    f"session {session_id}"
                )
        view = MaterializedView(self._engine, session, spec)
        with self._views_lock:
            if key in self._views:  # lost a registration race
                view.stop()
                raise ValueError(
                    f"pipeline {spec.name!r} is already registered on "
                    f"session {session_id}"
                )
            self._views[key] = view
        if self._journal is not None:
            self._journal.record_pipeline(
                session_id, spec.name, spec.to_dict()
            )
        out: Dict[str, Any] = {
            "session_id": session_id,
            "name": spec.name,
            "progress": spec.progress,
            "interval": spec.interval,
        }
        # ticker FIRST: the registration stands even when the initial
        # step fails (bad first file, NULL keys) — the error rides the
        # response, the pipeline stays registered and keeps ticking
        # (the step rolled back, so a fixed source folds cleanly later)
        view.start()
        if step:
            try:
                out["report"] = view.step()
            except Exception as ex:
                self._engine.log.warning(
                    "fugue_tpu serve: initial step of pipeline %s.%s "
                    "failed (%s: %s); registration stands",
                    session_id, spec.name, type(ex).__name__, ex,
                )
                out["report"] = {
                    "pipeline": spec.name,
                    "error": f"{type(ex).__name__}: {ex}",
                }
        return out

    def _get_view(self, session_id: str, name: str) -> Any:
        self._sessions.get(session_id)  # 404 + touch
        with self._views_lock:
            view = self._views.get((session_id, name))
        if view is None:
            raise KeyError(
                f"no pipeline {name!r} registered on session {session_id}"
            )
        return view

    def list_pipelines(self, session_id: str) -> List[Dict[str, Any]]:
        self._sessions.get(session_id)
        with self._views_lock:
            views = [
                v for (sid, _), v in sorted(self._views.items())
                if sid == session_id
            ]
        return [v.describe() for v in views]

    def describe_pipeline(
        self, session_id: str, name: str
    ) -> Dict[str, Any]:
        return self._get_view(session_id, name).describe()

    def step_pipeline(
        self, session_id: str, name: str, force_refresh: bool = False
    ) -> Dict[str, Any]:
        """Run one micro-batch of a registered pipeline NOW (the manual
        complement of the interval ticker; concurrent steps coalesce)."""
        self._reject_if_unhealthy()
        return self._get_view(session_id, name).step(
            force_refresh=force_refresh
        )

    def remove_pipeline(
        self, session_id: str, name: str, drop_table: bool = False
    ) -> Dict[str, Any]:
        view = self._get_view(session_id, name)
        with self._views_lock:
            self._views.pop((session_id, name), None)
        view.remove(drop_table=drop_table)
        if self._journal is not None:
            self._journal.forget_pipeline(session_id, name)
        return {
            "removed": name,
            "session_id": session_id,
            "dropped_table": drop_table,
        }

    def _restore_views(
        self, journaled: Dict[str, Dict[str, Any]], only: Any = None
    ) -> int:
        """Rebuild pipeline objects from journaled session records (the
        restart/adoption path). Each pipeline's progress manifest
        restores its accumulator state; a batch whose commit landed but
        whose refresh never confirmed re-emits on its first step. Never
        raises — a broken spec loses one view, not the daemon."""
        from fugue_tpu.stream.pipeline import PipelineSpec
        from fugue_tpu.stream.view import MaterializedView

        restored = 0
        for sid, rec in sorted(journaled.items()):
            if only is not None and sid not in only:
                continue
            session = self._sessions.peek(sid)
            if session is None:
                continue
            for name, spec_dict in sorted(
                (rec.get("pipelines") or {}).items()
            ):
                key = (sid, name)
                with self._views_lock:
                    if key in self._views:
                        continue
                try:
                    view = MaterializedView(
                        self._engine, session,
                        PipelineSpec.from_dict(spec_dict),
                    )
                except Exception as ex:
                    self._engine.log.warning(
                        "fugue_tpu serve: could not restore pipeline "
                        "%s.%s (%s: %s); its journal record is kept",
                        sid, name, type(ex).__name__, ex,
                    )
                    continue
                with self._views_lock:
                    self._views[key] = view
                view.start()
                restored += 1
        return restored

    def _drop_session_views(self, session_id: str) -> None:
        """A closing session takes its views down with it (tickers
        stopped, progress manifests cleared; the journal records die
        with the session record)."""
        with self._views_lock:
            keys = [k for k in self._views if k[0] == session_id]
            views = [self._views.pop(k) for k in keys]
        for v in views:
            try:
                v.remove(drop_table=False)
            except Exception:  # pragma: no cover - best-effort cleanup
                pass

    def _sweep_views(self) -> None:
        """Supervisor tick hook: a view whose session expired (TTL
        sweep) must stop ticking — peek, never get, so the sweep itself
        cannot keep an abandoned session alive."""
        with self._views_lock:
            items = list(self._views.items())
        for (sid, name), view in items:
            if self._sessions.peek(sid) is not None:
                continue
            with self._views_lock:
                self._views.pop((sid, name), None)
            try:
                # remove, not stop: the expired session's journal record
                # (pipeline specs included) is gone, so an orphaned
                # progress manifest would sit on shared fs forever
                view.remove(drop_table=False)
            except Exception:  # pragma: no cover - best-effort cleanup
                pass

    def _stop_views(self) -> None:
        """Daemon shutdown: stop tickers, KEEP progress manifests and
        journal records — the next daemon on this state path rebuilds
        and resumes the pipelines."""
        with self._views_lock:
            views = list(self._views.values())
            self._views.clear()
        for v in views:
            try:
                v.stop()
            except Exception:  # pragma: no cover - best-effort cleanup
                pass

    def _memory_budget_bytes(self) -> int:
        """Governed device-byte budget (0 = ungoverned) — what the
        predictive admission gate plans its in-flight predictions
        against."""
        mem = getattr(self._engine, "memory_stats", None)
        if not isinstance(mem, dict) or not mem.get("enabled"):
            return 0
        return int(mem.get("budget_bytes") or 0)

    def memory_pressure(self) -> float:
        """Device-tier fill fraction of the governed budget (0.0 when
        ungoverned) — the admission controller's memory signal, read
        from the PR 4 ledger snapshot."""
        mem = getattr(self._engine, "memory_stats", None)
        if not isinstance(mem, dict) or not mem.get("enabled"):
            return 0.0
        budget = mem.get("budget_bytes") or 0
        if budget <= 0:
            return 0.0
        return float((mem.get("tiers") or {}).get("device", 0)) / budget

    def _count_reject(self, kind: str) -> None:
        self._m_reject.labels(kind=kind).inc()

    def _collect_serve_gauges(self) -> None:
        """Scrape-time collector: pull-model serve gauges (breaker
        states as labeled gauges, queue depth, memory pressure, uptime,
        live sessions) computed when the registry is read."""
        metrics = self._engine.metrics
        g = metrics.gauge(
            "fugue_serve_breaker_states",
            "circuit breakers currently in each state",
            ["state"],
        )
        for state, n in self._supervisor.breaker_state_counts().items():
            g.labels(state=state).set(n)
        metrics.gauge(
            "fugue_serve_breaker_trips",
            "total breaker trips since daemon start",
        ).labels().set(self._supervisor.breaker_stats()["trips"])
        metrics.gauge(
            "fugue_serve_queue_depth", "queued (not yet running) jobs"
        ).labels().set(self._scheduler.backlog())
        metrics.gauge(
            "fugue_serve_memory_pressure",
            "device-tier fill fraction of the governed memory budget",
        ).labels().set(self.memory_pressure())
        metrics.gauge(
            "fugue_serve_sessions", "live serve sessions"
        ).labels().set(self._sessions.count())
        if self._admission is not None:
            metrics.gauge(
                "fugue_serve_predicted_drain_seconds",
                "predicted seconds until the job backlog drains "
                "(predictive scheduler)",
            ).labels().set(self._scheduler.predicted_drain_secs())
            metrics.gauge(
                "fugue_serve_predicted_inflight_bytes",
                "sum of running jobs' predicted peak device bytes",
            ).labels().set(self._admission.inflight_bytes())
        metrics.gauge(
            "fugue_serve_uptime_seconds", "seconds since daemon start"
        ).labels().set(
            time.time() - self._started_at
            if self._started_at is not None
            else 0.0
        )

    def _reject_if_unhealthy(self) -> None:
        """503 + Retry-After while draining/stopping. Checked BEFORE the
        session lookup too: a stopping daemon tears sessions down while
        the health state is still draining, and a racing submission must
        see the retryable rejection, never a fail-fast 404."""
        if not self._health.healthy:
            self._count_reject("draining")
            raise BackpressureError(
                f"daemon is {self._health.state}; not accepting submissions",
                retry_after=max(1.0, self._health.drain_remaining()),
            )

    def _admit(self, session_id: str, priority: int = 0) -> None:
        """Admission control for one submission; raises an
        :class:`AdmissionError` subtype (503/429 + Retry-After) when the
        daemon must shed load instead of queueing it. The caller has
        already passed :meth:`_reject_if_unhealthy` (before its session
        lookup), so this starts at the load signals."""
        if self._max_queue > 0 and self._scheduler.backlog() >= self._max_queue:
            self._count_reject("queue_full")
            raise BackpressureError(
                f"job queue is full ({self._max_queue} queued)",
                retry_after=1.0,
            )
        if self._admission is not None and self._admission_max_wait > 0:
            # predictive shedding (ISSUE 18): when the backlog's
            # PREDICTED drain exceeds the configured wait, shed in
            # priority order — the overload ratio sets the priority
            # floor a submission must clear, so cheap excess load drops
            # first while important work keeps landing; Retry-After is
            # the predicted drain itself, so backed-off clients return
            # when the queue is actually expected to have room. Never
            # touches accepted (queued/running) work: shedding happens
            # strictly at the door.
            drain = self._scheduler.predicted_drain_secs()
            ratio = drain / self._admission_max_wait
            if ratio > 1.0 and int(priority) < int(ratio):
                self._count_reject("shed")
                raise BackpressureError(
                    f"predicted queue drain {drain:.2f}s exceeds the "
                    f"admission wait budget {self._admission_max_wait:.2f}s "
                    f"(overload x{ratio:.1f}); submissions below priority "
                    f"{int(ratio)} are shed",
                    retry_after=max(1.0, drain),
                )
        if self._memory_reject > 0 and self._admission is None:
            # reactive observed-fill rejection (PR 6). Under the
            # predictive policy this check is OFF by design: jobs are
            # admitted and QUEUED, and the scheduler's predicted-memory
            # gate holds them until the in-flight prediction has room —
            # admit-or-queue on prediction, not reject on observation.
            pressure = self.memory_pressure()
            if pressure >= self._memory_reject:
                self._count_reject("memory_pressure")
                raise BackpressureError(
                    f"device memory pressure {pressure:.2f} is over the "
                    f"admission threshold {self._memory_reject:.2f}",
                    retry_after=2.0,
                )
        if (
            self._session_max_jobs > 0
            and self._scheduler.active_count(session_id)
            >= self._session_max_jobs
        ):
            self._count_reject("session_cap")
            raise SessionBusyError(
                f"session {session_id} already has "
                f"{self._session_max_jobs} jobs queued/running",
                retry_after=1.0,
            )
        try:
            self._supervisor.admit_session(session_id)
        except AdmissionError:
            self._count_reject("breaker_open")
            raise

    def submit(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        wait: bool = True,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
        request_id: Optional[str] = None,
        profile: bool = False,
        priority: int = 0,
        deadline: float = 0.0,
    ) -> ServeJob:
        self._reject_if_unhealthy()
        self._sessions.get(session_id)  # 404 early + touches the session
        self._admit(session_id, priority=priority)
        job = ServeJob(
            session_id,
            sql,
            save_as=save_as,
            timeout=timeout,
            collect=collect,
            limit=limit,
            request_id=request_id,
            profile=profile,
            priority=priority,
            deadline=deadline,
        )
        if self._admission is not None:
            # submit-time cost: stats-store-backed for repeat queries
            # (the execution path feeds the sql→fingerprint map),
            # registered defaults for first-timers
            job.cost = self._admission.model.estimate_sql(sql)
        # under an active request trace the job gets its serve.job span
        # NOW: queue wait is inside it, so traces attribute time spent
        # queued behind the scheduler separately from execution
        cur = current_span()
        if cur is not None:
            job.obs_trace = cur.trace
            job.obs_span = cur.trace.start_span(
                "serve.job",
                cur,
                {"job_id": job.job_id, "session_id": session_id},
            )
        if not wait and self._journal is not None:
            # journal BEFORE the queue: a crash between accept and
            # dispatch still resumes the job on restart
            self._journal.record_job(job)
        try:
            self._scheduler.submit(job)
        except Exception:
            if not wait and self._journal is not None:
                self._journal.finish_job(job.job_id)
            # _admit may have claimed a half-open probe slot: release it
            self._supervisor.note_cancelled(session_id, None)
            if job.obs_span is not None:
                job.obs_span.set_attr(status="rejected")
                job.obs_span.finish()
            raise
        if wait:
            # bounded: a wedged job must not pin the caller (an HTTP
            # handler thread) forever — on expiry the live snapshot goes
            # back (status still queued/running) and the client polls
            # /v1/jobs/<id> exactly like an async submission
            job.done_event.wait(
                timeout=self._sync_wait if self._sync_wait > 0 else None
            )
        return job

    def status(self) -> Dict[str, Any]:
        self._sessions.sweep()
        engine_stats: Dict[str, Any] = {
            "type": type(self._engine).__name__,
            "parallelism": self._engine.get_current_parallelism(),
        }
        mem = getattr(self._engine, "memory_stats", None)
        if isinstance(mem, dict):
            engine_stats["memory"] = mem
        fallbacks = getattr(self._engine, "fallbacks", None)
        if isinstance(fallbacks, dict):
            engine_stats["fallbacks"] = fallbacks
        # historical dict shapes, now views over the metric families
        # (minus the pre-daemon baseline on caller-owned engines)
        fault_totals = {
            k: v - self._fault_base.get(k, 0)
            for k, v in self._m_fault.as_int_dict().items()
        }
        reject_totals = {
            k: v - self._reject_base.get(k, 0)
            for k, v in self._m_reject.as_int_dict().items()
        }
        fault_totals["integrity_rejected"] += (
            self._sessions.integrity_rejected()
        )
        counts = self._scheduler.counts()
        health = self._health.describe()
        if self._health.state != "healthy":
            health["jobs_in_flight"] = counts["queued"] + counts["running"]
            if self._drain_result is not None:
                health["drain_result"] = dict(self._drain_result)
        uptime = (
            round(time.time() - self._started_at, 3)
            if self._started_at is not None
            else 0.0
        )
        from fugue_tpu import __version__

        # ISSUE 10: compile_cache reads the plan cache's EXACT
        # program-handle lookup counters (hit = a compiled handle was
        # reused) instead of the per-dispatch jax-cache-growth heuristic
        compile_cache = getattr(
            self._engine,
            "plan_cache_stats",
            getattr(self._engine, "compile_cache_stats", None),
        )
        plan_cache = dict(self._plan_cache.stats())
        plan_cache["serve_result"] = {
            str(k): v for k, v in self._m_result_cache.as_int_dict().items()
        }
        out: Dict[str, Any] = {
            "uptime_seconds": uptime,
            "uptime_secs": uptime,
            "version": __version__,
            "compile_cache": (
                dict(compile_cache)
                if isinstance(compile_cache, dict)
                else {"hits": 0, "misses": 0}
            ),
            "plan_cache": plan_cache,
            "health": health,
            "engine": engine_stats,
            "sessions": {
                "count": self._sessions.count(),
                "active": self._sessions.describe(),
            },
            "jobs": counts,
            "fault_stats": fault_totals,
            "backpressure": {
                "queue_depth": self._scheduler.backlog(),
                "max_queue": self._max_queue,
                "memory_pressure": round(self.memory_pressure(), 4),
                "rejections": reject_totals,
                "scheduler": self._scheduler_policy,
            },
            "supervisor": {
                "breakers": self._supervisor.breaker_stats(),
                "wedged_jobs_cancelled": self._supervisor.wedged_jobs,
                "heartbeat_timeout": self._supervisor.heartbeat_timeout,
            },
        }
        if self._admission is not None:
            admission = self._admission.describe()
            admission["max_predicted_wait"] = self._admission_max_wait
            out["admission"] = admission
        if self._journal is not None:
            out["durable"] = self._journal.describe()
            out["recovery"] = dict(self._recovery)
        if self._stats_store is not None:
            out["stats_store"] = self._stats_store.describe()
        if self._restart_phases or self._first_query:
            # time_to_first_query phase split (ISSUE 11): journal-reload
            # and cache-load from startup, compile/dispatch from the
            # engine's dispatch clock over the first executed query.
            # A SIBLING of "recovery" (whose keys are a stable contract)
            out["cold_start"] = {
                "phases": dict(self._restart_phases),
                "first_query": dict(self._first_query or {}),
                "warming": self._warming,
            }
        if getattr(self._engine, "_exec_enabled", False):
            out["exec_cache"] = self._engine.exec_cache_stats
        if getattr(self._engine, "is_degraded", False):
            out["device_recovery"] = {
                "lost_devices": list(self._engine.lost_devices),
                "surviving_devices": int(
                    self._engine.surviving_device_count
                ),
                "recoveries": int(self._engine.device_recoveries),
            }
        return out

    # ---- job execution (scheduler worker threads) ------------------------
    def _execute_job(self, job: ServeJob) -> Dict[str, Any]:
        # re-attach the submitting request's trace on THIS worker
        # thread: everything below (workflow.run → tasks → attempts →
        # engine compile/execute/transfer) lands under the job's span.
        # A job whose request LOST the sampling draw runs suppressed, so
        # the workflow layer does not re-draw and export an
        # uncorrelated trace of its own.
        if self._obs.enabled and job.obs_span is None:
            with suppress_tracing():
                return self._timed_execute(job)
        with activate(job.obs_span):
            with start_span("serve.execute"):
                return self._timed_execute(job)

    def _timed_execute(self, job: ServeJob) -> Dict[str, Any]:
        """Record the FIRST executed query's wall clock split into
        compile / dispatch / disk-load (engine dispatch clock deltas)
        plus its XLA compile count — the ``time_to_first_query``
        evidence the restart-recovery bench reads from /v1/status."""
        if self._first_query is not None or not hasattr(
            self._engine, "dispatch_time_stats"
        ):
            return self._execute_job_impl(job)
        with self._first_query_lock:
            # claim without holding the lock across execution (a held
            # lock would serialize every job queued behind the first)
            if self._first_query is not None:
                claimed = False
            else:
                claimed = True
                self._first_query = {}  # claimed; filled below
        if not claimed:
            return self._execute_job_impl(job)
        d0 = self._engine.dispatch_time_stats
        c0 = self._engine.compile_cache_stats
        t0 = time.monotonic()
        try:
            return self._execute_job_impl(job)
        finally:
            d1 = self._engine.dispatch_time_stats
            c1 = self._engine.compile_cache_stats
            self._first_query = {
                "total_secs": round(time.monotonic() - t0, 4),
                "compile_secs": round(d1["compile"] - d0["compile"], 4),
                "dispatch_secs": round(d1["execute"] - d0["execute"], 4),
                "disk_load_secs": round(
                    d1["disk_load"] - d0["disk_load"], 4
                ),
                "xla_compiles": c1["misses"] - c0["misses"],
            }

    def _execute_job_impl(self, job: ServeJob) -> Dict[str, Any]:
        job.beat()
        session = self._sessions.get(job.session_id)
        dag = FugueSQLWorkflow()
        # snapshot the epoch BEFORE the table frames: a concurrent
        # save_table between the snapshot and the key build must make
        # this job's payload land under the OLD epoch (never served
        # again), not under the new one with pre-save data
        cache_epoch = session.cache_epoch
        # content keys snapshot with the epoch: a save racing this job
        # leaves the payload under the PRE-save keys (equivalent to the
        # job having run just before the save), never the new ones
        pre_content_keys = (
            session.table_content_keys() if self._fleet_result_dir else None
        )
        sources = session.table_frames()
        try:
            dag._sql(job.sql, {}, **sources)
        except Exception:
            # the query never compiled, so there is no DAG uuid to key
            # the breaker on — fall back to a deterministic text hash so
            # repeat-submitting a compile-poison query still quarantines
            from fugue_tpu.utils.hash import to_uuid

            job.fingerprint = to_uuid(
                "serve.compile", sorted(sources), job.sql
            )
            self._supervisor.admit_query(job.fingerprint)
            raise
        # the DAG's deterministic uuid (built from task uuids) is the
        # breaker's query fingerprint: same query over the same session
        # tables -> same key, across submissions and daemon restarts
        job.fingerprint = dag.__uuid__()
        if self._admission is not None:
            # cost-model feedback: the NEXT submission of this SQL text
            # resolves to this fingerprint's stats-store history at
            # admission time, before any compilation
            from fugue_tpu.serve.admission import sql_cost_key

            self._admission.model.note_fingerprint(
                sql_cost_key(job.sql), job.fingerprint
            )
        self._supervisor.admit_query(job.fingerprint)
        has_result = dag.last_df is not None
        # cross-request result cache: only PURE queries (deterministic
        # builtins, no output tasks, no user yields, no save_as) are
        # eligible — a cached payload must never skip a side effect.
        # The key folds the session id and its catalog epoch so another
        # session's same-shaped tables or a post-save resubmission can
        # never be served the wrong payload.
        cache_key = None
        fleet_cache_uri = None
        if (
            (self._result_cache_on or self._fleet_result_dir)
            and has_result
            and job.save_as is None
            and job.collect
            and len(dag.yields) == 0
            # a profile-requested job must actually EXECUTE (EXPLAIN
            # ANALYZE measures a run, a cached payload has no profile)
            and not job.profile_requested
        ):
            from fugue_tpu.optimize.rewrite import tasks_are_pure

            # session table frames only change via save_table, which
            # bumps cache_epoch in this key: frame inputs are stable
            if tasks_are_pure(dag.tasks, frame_inputs_stable=True):
                if self._result_cache_on:
                    cache_key = (
                        "serve",
                        job.session_id,
                        cache_epoch,
                        job.fingerprint,
                        job.limit,
                    )
                # fleet tier: content-addressed (DAG fingerprint + the
                # tables' artifact sha256s), so the key is valid on ANY
                # replica and for ANY session with identical content —
                # the cross-replica warm-start path. Sessions with an
                # unverifiable table (no durable artifact) are ineligible
                if self._fleet_result_dir and pre_content_keys is not None:
                    from fugue_tpu.utils.hash import to_uuid

                    fleet_cache_uri = self._engine.fs.join(
                        self._fleet_result_dir,
                        to_uuid(
                            "serve.fleet.result",
                            job.fingerprint,
                            str(job.limit),
                            pre_content_keys,
                        )
                        + ".json",
                    )
        if cache_key is not None:
            cached = self._plan_cache.get_result(cache_key)
            if cached is not None:
                self._m_result_cache.labels(result="hit").inc()
                session.touch()
                payload = dict(cached)
                if "result" in payload:
                    payload["result"] = dict(payload["result"])
                return payload
            self._m_result_cache.labels(result="miss").inc()
        if fleet_cache_uri is not None:
            from fugue_tpu.workflow.manifest import read_json

            entry = read_json(self._engine.fs, fleet_cache_uri)
            if isinstance(entry, dict) and isinstance(
                entry.get("payload"), dict
            ):
                self._m_result_cache.labels(result="fs_hit").inc()
                session.touch()
                return dict(entry["payload"])
            self._m_result_cache.labels(result="fs_miss").inc()
        if has_result:
            dag.last_df.yield_dataframe_as(_RESULT_YIELD)
        gov = getattr(self._engine, "memory_governor", None)
        # tenant_scope is THREAD-local: it covers the run's serial task
        # execution (the inner runner defaults to concurrency 1, in
        # thread) and this thread's save/collect materializations; a
        # parallel inner runner's worker threads are outside it, which
        # is fine — durable ownership comes from assign_tenant at
        # save_table time, and unsaved frames die with the job anyway
        scope = (
            gov.tenant_scope(job.session_id)
            if gov is not None
            else nullcontext()
        )
        profile_scope = (
            force_profiling() if job.profile_requested else nullcontext()
        )
        with scope:
            with profile_scope:
                wres = dag.run(self._engine, cancel_token=job.token)
            job.beat()
            # per-task runtime profile (EXPLAIN ANALYZE): present when
            # the job requested it or daemon conf profiles every run —
            # served at GET /v1/jobs/<id>/profile; the workflow layer
            # already persisted the observation into the stats store
            job.profile = wres.profile()
            self._note_fault_stats(wres.fault_stats)
            payload: Dict[str, Any] = {
                "yields": sorted(
                    k for k in dag.yields if k != _RESULT_YIELD
                ),
            }
            if not has_result:
                return payload
            df = wres[_RESULT_YIELD]
            if job.save_as is not None:
                session.save_table(job.save_as, df)
                job.beat()
                payload["saved_as"] = job.save_as
            if job.collect:
                from fugue_tpu.workflow.fault import engine_dispatch_guard

                # head() on a device frame reads back through device
                # programs: serialize with concurrent jobs; the job's
                # token makes the wait cancellable
                with engine_dispatch_guard(self._engine, job.token):
                    local = df.head(job.limit + 1)
                job.beat()
                rows = local.as_array(type_safe=True)
                truncated = len(rows) > job.limit
                payload["result"] = {
                    "columns": list(df.schema.names),
                    "types": str(df.schema),
                    "rows": rows[: job.limit],
                    "row_count": min(len(rows), job.limit),
                    "truncated": truncated,
                }
        if cache_key is not None:
            result = payload.get("result") or {}
            nbytes = 64 + 16 * len(result.get("rows") or []) * max(
                1, len(result.get("columns") or [])
            )
            stored = dict(payload)
            if "result" in stored:
                stored["result"] = dict(stored["result"])
            self._plan_cache.put_result(
                cache_key, stored, nbytes, tag=job.session_id
            )
        if fleet_cache_uri is not None:
            self._store_fleet_result(
                session, pre_content_keys, fleet_cache_uri, payload
            )
        session.touch()
        return payload

    def _store_fleet_result(
        self,
        session: ServeSession,
        pre_content_keys: Any,
        uri: str,
        payload: Dict[str, Any],
    ) -> None:
        """Best-effort store into the fleet's shared fs result cache —
        only when the session's table content is STILL what the key was
        computed from (a save racing the run must not publish new data
        under the old content keys). Failures count, never raise."""
        try:
            if session.table_content_keys() != pre_content_keys:
                return
            from fugue_tpu.serve.http import dumps
            from fugue_tpu.workflow.manifest import atomic_json_write

            # json-roundtrip through the serve encoder: result cells may
            # be numpy/temporal scalars the plain encoder rejects, and
            # an fs entry must read back exactly like an HTTP payload
            import json as _json

            normalized = _json.loads(dumps(payload).decode("utf-8"))
            atomic_json_write(
                self._engine.fs, uri,
                {"saved_at": time.time(), "payload": normalized},
            )
            self._m_result_cache.labels(result="fs_store").inc()
        except Exception as ex:
            self._m_result_cache.labels(result="fs_error").inc()
            self._engine.log.warning(
                "fugue_tpu serve: fleet result-cache store to %s failed "
                "(%s: %s); serving continues",
                uri, type(ex).__name__, ex,
            )

    def _job_finished(self, job: ServeJob) -> None:
        """Scheduler ``on_finish`` observer: job-journal cleanup,
        observability settlement (span end, latency histogram,
        slow-query log, trace export) and breaker accounting
        (cancellations are neutral; a breaker's own rejection never
        counts as a fresh failure)."""
        if self._journal is not None:
            self._journal.finish_job(job.job_id)
        self._obs_job_finished(job)
        if job.status == CANCELLED:
            # verdict-free for the breakers — but the job may have held
            # a half-open probe slot, which must go back
            self._supervisor.note_cancelled(job.session_id, job.fingerprint)
            return
        err_type = (job.error or {}).get("error")
        if err_type in _BREAKER_ERRORS:
            # a breaker's own rejection is verdict-free — but the
            # submit-time session admission may still hold a half-open
            # probe slot, which must go back (the query-fingerprint
            # breaker refused, so it claimed nothing)
            self._supervisor.note_cancelled(job.session_id, None)
            return
        self._supervisor.note_result(
            job.session_id, job.fingerprint, failed=job.status == ERROR
        )

    def _note_fault_stats(self, stats: Dict[str, Any]) -> None:
        self._m_fault.labels(kind="runs").inc()
        for key in (
            "retries", "recoveries", "degradations",
            "integrity_rejected",
        ):
            n = sum((stats.get(key) or {}).values())
            if n:
                self._m_fault.labels(kind=key).inc(n)
        resumed = len(stats.get("resumed") or [])
        if resumed:
            self._m_fault.labels(kind="resumed").inc(resumed)

    def _obs_job_finished(self, job: ServeJob) -> None:
        """Settle one finished job's observability: latency histogram,
        span end + trace export, slow-query record. Best-effort — never
        raises into the scheduler's finish path."""
        try:
            duration = None
            if job.started_at is not None and job.finished_at is not None:
                duration = job.finished_at - job.started_at
                self._m_job_secs.labels(status=job.status).observe(duration)
            if job.obs_span is not None:
                job.obs_span.set_attr(status=job.status)
                job.obs_span.finish()
            if duration is not None:
                maybe_log_slow_query(
                    job.obs_trace,
                    duration * 1000.0,
                    self._obs.slow_query_ms,
                    log=self._engine.log,
                    registry=self._engine.metrics,
                    # profiled jobs name their top-3 most expensive
                    # tasks (name, callsite, phase split) in the record
                    profile=job.profile,
                    job_id=job.job_id,
                    session_id=job.session_id,
                    request_id=job.request_id,
                    status=job.status,
                )
            if job.obs_trace is not None:
                # export when this job was the LAST open piece of its
                # request trace (async submissions; sync ones usually
                # export at HTTP response time)
                finalize_trace(
                    job.obs_trace,
                    self._obs,
                    fs=self._engine.fs,
                    log=self._engine.log,
                    registry=self._engine.metrics,
                    finish_root=False,
                )
        except Exception:  # pragma: no cover - observability best-effort
            pass

    # ---- HTTP routing ----------------------------------------------------
    def render_metrics(self) -> str:
        """The engine registry as Prometheus text exposition — the body
        of ``GET /v1/metrics``."""
        return self._engine.metrics.render()

    @staticmethod
    def _route_family(path: str) -> str:
        """Bounded-cardinality route label for request metrics: the
        first path segment under /v1 (health/status/metrics/sessions/
        jobs), never raw ids."""
        parts = [p for p in path.split("?", 1)[0].split("/") if p]
        if len(parts) >= 2 and parts[0] == "v1":
            return parts[1]
        return "unknown"

    def handle_api(
        self,
        method: str,
        path: str,
        payload: Dict[str, Any],
        request_id: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        """Route one API request; returns (status, JSON-safe response,
        extra headers). Never raises: handler failures become structured
        error payloads (KeyError -> 404, admission/backpressure -> the
        error's own status + Retry-After header, bad input -> 400, the
        rest -> 500). Every response carries ``X-Request-Id`` — the
        (sanitized) inbound header or a generated id — and, with
        ``fugue.obs.enabled``, the request runs under a trace root whose
        id IS the correlation id."""
        rid = clean_request_id(request_id) or new_request_id()
        trace, root = open_trace(
            self._obs,
            "http.request",
            trace_id=rid,
            request_id=rid,
            method=method,
        )
        t0 = time.monotonic()
        status = 500
        try:
            with activate(root):
                status, resp, headers = self._handle(
                    method, path, payload, rid
                )
        finally:
            elapsed = time.monotonic() - t0
            if root is not None:
                root.set_attr(status=status)
                root.finish()
            route = self._route_family(path)
            self._m_requests.labels(route=route, status=str(status)).inc()
            self._m_request_secs.labels(route=route).observe(elapsed)
            if trace is not None:
                finalize_trace(
                    trace,
                    self._obs,
                    fs=self._engine.fs,
                    log=self._engine.log,
                    registry=self._engine.metrics,
                    finish_root=False,
                )
        out_headers = dict(headers)
        out_headers["X-Request-Id"] = rid
        return status, resp, out_headers

    def _handle(
        self,
        method: str,
        path: str,
        payload: Dict[str, Any],
        request_id: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        try:
            fault_point("serve.http", f"{method} {path}")
            out = self._route(method, path, payload, request_id)
            if len(out) == 2:
                status, resp = out  # type: ignore[misc]
                return status, resp, {}
            return out  # type: ignore[return-value]
        except KeyError as ex:
            return 404, {"error": structured_error(ex)}, {}
        except AdmissionError as ex:
            return (
                ex.status,
                {
                    "error": structured_error(ex),
                    "retry_after": ex.retry_after,
                },
                {"Retry-After": str(max(1, int(round(ex.retry_after or 1))))},
            )
        except (ValueError, TypeError) as ex:
            return 400, {"error": structured_error(ex)}, {}
        except Exception as ex:  # pragma: no cover - defensive
            return 500, {"error": structured_error(ex)}, {}

    def _route(
        self,
        method: str,
        path: str,
        payload: Dict[str, Any],
        request_id: Optional[str] = None,
    ) -> Any:
        parts = [p for p in path.split("?", 1)[0].split("/") if p]
        if not parts or parts[0] != "v1":
            raise KeyError(f"unknown path {path}")
        route = parts[1:]
        if route == ["health"] and method == "GET":
            # pre-warm gating: an LB must not route queries here while
            # cached executables are still loading — the state reads
            # "warming" and the daemon answers 503 exactly like a drain
            # (submissions are still ACCEPTED; only readiness is gated)
            ok = self.ready
            state = (
                "warming"
                if self._warming and self._health.healthy
                else self._health.state
            )
            body = {"ok": ok, "state": state}
            if (
                ok
                and state == "healthy"
                and getattr(self._engine, "is_degraded", False)
            ):
                # a device died and the engine rebuilt onto the
                # survivors: still serving (200) but advertising reduced
                # capacity, so the fleet autoscaler treats this replica
                # as sustained pressure (spawn healthy, drain-retire us)
                body["state"] = "degraded"
                body["surviving_devices"] = int(
                    self._engine.surviving_device_count
                )
                body["lost_devices"] = list(self._engine.lost_devices)
            return (200 if ok else 503), body
        if route == ["status"] and method == "GET":
            return 200, self.status()
        if route == ["sessions"]:
            if method == "POST":
                ttl = payload.get("ttl")
                session = self.create_session(
                    ttl=None if ttl is None else float(ttl)
                )
                return 200, {
                    "session_id": session.session_id,
                    "ttl": session.ttl,
                }
            if method == "GET":
                self._sessions.sweep()
                return 200, {"sessions": self._sessions.describe()}
        if len(route) >= 2 and route[0] == "sessions":
            sid = route[1]
            rest = route[2:]
            if not rest and method == "GET":
                return 200, self._sessions.get(sid).describe()
            if (not rest and method == "DELETE") or (
                rest == ["close"] and method == "POST"
            ):
                return 200, self.close_session(sid)
            if rest == ["sql"] and method == "POST":
                return self._route_sql(sid, payload, request_id)
            if rest and rest[0] == "pipelines":
                prest = rest[1:]
                if not prest and method == "POST":
                    return 200, self.register_pipeline(
                        sid, payload,
                        step=bool(payload.get("step", True)),
                    )
                if not prest and method == "GET":
                    return 200, {"pipelines": self.list_pipelines(sid)}
                if len(prest) == 1 and method == "GET":
                    return 200, self.describe_pipeline(sid, prest[0])
                if len(prest) == 1 and method == "DELETE":
                    return 200, self.remove_pipeline(
                        sid, prest[0],
                        drop_table=bool(payload.get("drop_table", False)),
                    )
                if (
                    len(prest) == 2
                    and prest[1] == "step"
                    and method == "POST"
                ):
                    return 200, self.step_pipeline(
                        sid, prest[0],
                        force_refresh=bool(
                            payload.get("force_refresh", False)
                        ),
                    )
        if route == ["admin", "adopt"] and method == "POST":
            state_path = payload.get("state_path")
            if not isinstance(state_path, str) or not state_path.strip():
                raise ValueError(
                    "payload must carry the source replica's 'state_path'"
                )
            return 200, {"adopted": self.adopt_state(state_path)}
        if len(route) >= 2 and route[0] == "jobs":
            jid = route[1]
            rest = route[2:]
            if not rest and method == "GET":
                return 200, self._scheduler.get(jid).snapshot()
            if rest == ["profile"] and method == "GET":
                return 200, self.job_profile(jid)
            if rest == ["cancel"] and method == "POST":
                return 200, self._scheduler.cancel(jid).snapshot(
                    include_result=False
                )
        raise KeyError(f"unknown route {method} {path}")

    def job_profile(self, job_id: str) -> Dict[str, Any]:
        """``GET /v1/jobs/<id>/profile``: the job's per-task runtime
        profile (EXPLAIN ANALYZE). 404 while the job is still running
        or when it was not profiled (submit with ``"profile": true`` or
        set ``fugue.obs.profile`` on the daemon)."""
        job = self._scheduler.get(job_id)
        if job.profile is None:
            raise KeyError(
                f"job {job_id} has no profile (status={job.status}; "
                "submit with 'profile': true, or set fugue.obs.profile "
                "with fugue.obs.enabled on the daemon)"
            )
        return {
            "job_id": job.job_id,
            "session_id": job.session_id,
            "status": job.status,
            "profile": job.profile.as_dict(),
            "text": job.profile.to_text(),
        }

    def explain_sql(self, session_id: str, sql: str) -> Dict[str, Any]:
        """The submission-time ``explain`` flag: compile the FugueSQL
        against the session's hot tables and return the static plan
        report WITHOUT executing anything (classic EXPLAIN). When the
        runtime-statistics store holds history for this query's
        fingerprint, the last observed per-task row counts ride along —
        the replay surface that survives restarts and adoption."""
        session = self._sessions.get(session_id)
        dag = FugueSQLWorkflow()
        dag._sql(sql, {}, **session.table_frames())
        report = dag.explain(engine=self._engine)
        fingerprint = dag.__uuid__()
        out: Dict[str, Any] = {
            "session_id": session_id,
            "fingerprint": fingerprint,
            "explain": {
                "text": report.to_text(),
                "plan": report.to_dict(),
            },
        }
        if self._stats_store is not None:
            latest = self._stats_store.latest(fingerprint)
            if latest is not None:
                out["observed"] = {
                    "recorded_at": latest.get("recorded_at"),
                    "total_ms": latest.get("total_ms"),
                    "rows": self._stats_store.observed_rows(fingerprint),
                    "observations": len(
                        self._stats_store.history(fingerprint)
                    ),
                }
        session.touch()
        return out

    def _route_sql(
        self,
        sid: str,
        payload: Dict[str, Any],
        request_id: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        sql = payload.get("sql")
        if not isinstance(sql, str) or not sql.strip():
            raise ValueError("payload must carry a non-empty 'sql' string")
        if bool(payload.get("explain", False)):
            # EXPLAIN: compile + report, never execute (health-gated
            # like a submission — a draining daemon sheds it)
            self._reject_if_unhealthy()
            return 200, self.explain_sql(sid, sql)
        mode = str(payload.get("mode", "sync")).lower()
        if mode not in ("sync", "async"):
            raise ValueError(f"mode must be sync|async, got {mode!r}")
        degraded = False
        if (
            mode == "sync"
            and self._sync_degrade_depth > 0
            and self._scheduler.backlog() >= self._sync_degrade_depth
        ):
            # under load a sync submit would park an HTTP worker behind
            # a deep queue: degrade to async and hand back the job id
            mode = "async"
            degraded = True
            self._count_reject("sync_degraded")
        # scheduling fields (ISSUE 18): "priority" (int, higher wins;
        # default 0) and "deadline" (relative seconds budget — the job
        # must START within it or it settles with a structured error;
        # 0/absent = none). Converted here to the absolute epoch the
        # scheduler compares against.
        priority = int(payload.get("priority", 0))
        deadline_secs = float(payload.get("deadline", 0.0) or 0.0)
        deadline = time.time() + deadline_secs if deadline_secs > 0 else 0.0
        job = self.submit(
            sid,
            sql,
            save_as=payload.get("save_as"),
            wait=mode == "sync",
            timeout=float(payload.get("timeout", 0.0)),
            collect=bool(payload.get("collect", True)),
            limit=int(payload.get("limit", 10_000)),
            request_id=request_id,
            profile=bool(payload.get("profile", False)),
            priority=priority,
            deadline=deadline,
        )
        if mode == "async":
            snap = job.snapshot(include_result=False)
            if degraded:
                snap["degraded_to_async"] = True
            return 202, snap
        return 200, job.snapshot()
