"""The daemon's HTTP layer: a JSON API on the hardened server machinery
from :mod:`fugue_tpu.rpc.http`.

:class:`ServeHTTPServer` subclasses :class:`HTTPRPCServer`, inheriting
its threaded lifecycle (start/stop idempotence, wedged-shutdown
reporting) and the daemon-hardening conf — request body cap
(``fugue.rpc.http_server.max_body_bytes``), per-request read timeout
(``.read_timeout``) — while swapping the pickle RPC protocol handler for
a JSON router. Every response is JSON; failures are the structured
``{"error": {"error": <type>, "message": <str>}}`` payload, never a
traceback.
"""

import json
from typing import TYPE_CHECKING, Any

from fugue_tpu.rpc.http import (
    HardenedRequestHandler,
    HTTPRPCServer,
    structured_error,
)

if TYPE_CHECKING:  # pragma: no cover
    from fugue_tpu.serve.daemon import ServeDaemon


def json_default(obj: Any) -> Any:
    """JSON fallback for engine result cells: numpy/jax scalars unwrap
    via ``.item()``, dates/timestamps via ``.isoformat()``, anything
    else stringifies."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    iso = getattr(obj, "isoformat", None)
    if callable(iso):
        return iso()
    return str(obj)


def dumps(payload: Any) -> bytes:
    return json.dumps(payload, default=json_default).encode("utf-8")


class _ServeAPIHandler(HardenedRequestHandler):
    # bound by the server factory (HTTPRPCServer.start_server)
    rpc_server: "ServeHTTPServer"

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        self._begin_request()
        self._route("GET", b"")

    def do_DELETE(self) -> None:  # noqa: N802
        # DELETE may carry a small JSON body (pipeline removal options);
        # absent Content-Length reads as empty, exactly like before
        self._begin_request()
        body = self.read_body()
        if body is None:
            return
        self._route("DELETE", body)

    def do_POST(self) -> None:  # noqa: N802
        # correlation id FIRST: even a 400/413 body rejection (written
        # inside read_body, before routing) must echo X-Request-Id
        self._begin_request()
        body = self.read_body()  # 413 already sent when over the cap
        if body is None:
            return
        self._route("POST", body)

    def _begin_request(self) -> None:
        self._request_id = self.headers.get("X-Request-Id")

    def _route(self, method: str, body: bytes) -> None:
        try:
            payload = json.loads(body.decode("utf-8")) if body else {}
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
        except ValueError as ex:
            self.send_error_payload(400, ex)
            return
        daemon = self.rpc_server.daemon
        if method == "GET" and self.path.split("?", 1)[0] == "/v1/metrics":
            # Prometheus scrape: text exposition, not the JSON plane
            try:
                text = daemon.render_metrics()
            except Exception as ex:  # pragma: no cover - defensive
                self.send_error_payload(500, ex)
                return
            self._send_bytes(
                200,
                text.encode("utf-8"),
                "text/plain; version=0.0.4; charset=utf-8",
            )
            return
        status, resp, headers = daemon.handle_api(
            method, self.path, payload, request_id=self._request_id
        )
        self._send_json(status, resp, headers)

    def _send_bytes(
        self,
        status: int,
        data: bytes,
        content_type: str,
        headers: Any = None,
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        merged = dict(headers or {})
        if "X-Request-Id" not in merged:
            # the router's echo when it ran; the raw inbound (or a
            # generated one) for failures answered before routing
            from fugue_tpu.serve.daemon import clean_request_id, new_request_id

            merged["X-Request-Id"] = (
                clean_request_id(getattr(self, "_request_id", None))
                or new_request_id()
            )
        for name, value in merged.items():
            # extra response headers from the router — Retry-After on
            # the backpressure/drain rejections, X-Request-Id everywhere
            self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(
        self, status: int, resp: Any, headers: Any = None
    ) -> None:
        self._send_bytes(status, dumps(resp), "application/json", headers)

    def send_error_payload(self, status: int, ex: BaseException) -> None:
        self._send_json(status, {"error": structured_error(ex)})


class ServeHTTPServer(HTTPRPCServer):
    """The daemon's JSON API server. ``conf`` uses the same
    ``fugue.rpc.http_server.*`` keys as the RPC server (the daemon maps
    ``fugue.serve.host``/``.port`` onto them before construction)."""

    handler_class = _ServeAPIHandler

    def __init__(self, daemon: "ServeDaemon", conf: Any = None):
        super().__init__(conf)
        self.daemon = daemon
