"""The daemon's HTTP layer: a JSON API on the hardened server machinery
from :mod:`fugue_tpu.rpc.http`.

:class:`ServeHTTPServer` subclasses :class:`HTTPRPCServer`, inheriting
its threaded lifecycle (start/stop idempotence, wedged-shutdown
reporting) and the daemon-hardening conf — request body cap
(``fugue.rpc.http_server.max_body_bytes``), per-request read timeout
(``.read_timeout``) — while swapping the pickle RPC protocol handler for
a JSON router. Every response is JSON; failures are the structured
``{"error": {"error": <type>, "message": <str>}}`` payload, never a
traceback.
"""

import json
from typing import TYPE_CHECKING, Any

from fugue_tpu.rpc.http import (
    HardenedRequestHandler,
    HTTPRPCServer,
    structured_error,
)

if TYPE_CHECKING:  # pragma: no cover
    from fugue_tpu.serve.daemon import ServeDaemon


def json_default(obj: Any) -> Any:
    """JSON fallback for engine result cells: numpy/jax scalars unwrap
    via ``.item()``, dates/timestamps via ``.isoformat()``, anything
    else stringifies."""
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    iso = getattr(obj, "isoformat", None)
    if callable(iso):
        return iso()
    return str(obj)


def dumps(payload: Any) -> bytes:
    return json.dumps(payload, default=json_default).encode("utf-8")


class _ServeAPIHandler(HardenedRequestHandler):
    # bound by the server factory (HTTPRPCServer.start_server)
    rpc_server: "ServeHTTPServer"

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        self._route("GET", b"")

    def do_DELETE(self) -> None:  # noqa: N802
        self._route("DELETE", b"")

    def do_POST(self) -> None:  # noqa: N802
        body = self.read_body()  # 413 already sent when over the cap
        if body is None:
            return
        self._route("POST", body)

    def _route(self, method: str, body: bytes) -> None:
        try:
            payload = json.loads(body.decode("utf-8")) if body else {}
            if not isinstance(payload, dict):
                raise ValueError("request body must be a JSON object")
        except ValueError as ex:
            self.send_error_payload(400, ex)
            return
        status, resp, headers = self.rpc_server.daemon.handle_api(
            method, self.path, payload
        )
        self._send_json(status, resp, headers)

    def _send_json(
        self, status: int, resp: Any, headers: Any = None
    ) -> None:
        data = dumps(resp)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for name, value in (headers or {}).items():
            # extra response headers from the router — Retry-After on
            # the backpressure/drain rejections
            self.send_header(name, str(value))
        self.end_headers()
        self.wfile.write(data)

    def send_error_payload(self, status: int, ex: BaseException) -> None:
        self._send_json(status, {"error": structured_error(ex)})


class ServeHTTPServer(HTTPRPCServer):
    """The daemon's JSON API server. ``conf`` uses the same
    ``fugue.rpc.http_server.*`` keys as the RPC server (the daemon maps
    ``fugue.serve.host``/``.port`` onto them before construction)."""

    handler_class = _ServeAPIHandler

    def __init__(self, daemon: "ServeDaemon", conf: Any = None):
        super().__init__(conf)
        self.daemon = daemon
