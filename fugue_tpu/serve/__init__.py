"""Multi-tenant serving: a long-lived daemon owning ONE persistent
execution engine that serves concurrent FugueSQL / workflow submissions
over HTTP — the role Spark's Thrift Server and Ray Serve play for the
reference backends (PAPER.md §2.7/§2.10), composed out of parts this
repo already has: the hardened HTTP layer (:mod:`fugue_tpu.rpc.http`),
the SQLEngine table catalog (device-resident for the jax engine), the
workflow runner's timeout/cancellation machinery, and the memory
governor's per-tenant fair-spill accounting.

The resilience plane (ISSUE 7) makes the daemon production-shaped:
durable crash-journaled state (:mod:`~fugue_tpu.serve.state`) with
restart rehydration of sessions/hot tables/async jobs, graceful drain
with 503 + ``Retry-After``, queue-depth/memory-pressure/per-session
admission control, circuit breakers + heartbeat supervision
(:mod:`~fugue_tpu.serve.supervisor`), client transient retry, and a
serve-plane chaos harness (see README "Serving resilience").

Quick start::

    from fugue_tpu.serve import ServeClient, ServeDaemon

    with ServeDaemon({"fugue.serve.max_concurrent": 8}) as daemon:
        client = ServeClient(*daemon.address)
        sid = client.create_session()
        client.sql(sid, "CREATE [[0],[1]] SCHEMA a:long", save_as="t")
        print(client.sql(sid, "SELECT COUNT(*) AS n FROM t")["result"])
        client.close_session(sid)
"""

from fugue_tpu.serve.admission import (
    CostEstimate,
    PredictiveAdmission,
    QueryCostModel,
)
from fugue_tpu.serve.autoscale import FleetAutoscaler
from fugue_tpu.serve.client import (
    ServeAPIError,
    ServeClient,
    ServeJobTimeoutError,
)
from fugue_tpu.serve.daemon import ServeDaemon
from fugue_tpu.serve.fleet import FleetRouter, ServeFleet
from fugue_tpu.serve.scheduler import JobScheduler, ServeJob
from fugue_tpu.serve.session import ServeSession, SessionManager
from fugue_tpu.serve.state import ServeStateJournal
from fugue_tpu.serve.supervisor import (
    AdmissionError,
    BackpressureError,
    CircuitBreaker,
    CircuitOpenError,
    EngineSupervisor,
    PoisonQueryError,
    SessionBusyError,
)

__all__ = [
    "AdmissionError",
    "BackpressureError",
    "CircuitBreaker",
    "CircuitOpenError",
    "CostEstimate",
    "EngineSupervisor",
    "FleetAutoscaler",
    "FleetRouter",
    "PredictiveAdmission",
    "QueryCostModel",
    "PoisonQueryError",
    "ServeAPIError",
    "ServeClient",
    "ServeDaemon",
    "ServeFleet",
    "ServeJobTimeoutError",
    "ServeStateJournal",
    "SessionBusyError",
    "JobScheduler",
    "ServeJob",
    "ServeSession",
    "SessionManager",
]
