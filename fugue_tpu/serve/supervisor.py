"""Engine supervision: health state machine, admission errors, circuit
breakers and the heartbeat watchdog.

**Health state machine.** The daemon is ``healthy`` → ``draining`` →
``stopped`` (one-way). ``draining`` (SIGTERM / ``stop(drain=True)``)
keeps the HTTP plane up so clients can poll in-flight jobs, but every
new submission answers 503 + ``Retry-After``; when the drain deadline
expires, still-running jobs are cancelled and abandoned, the state is
journaled, and the engine context closes.

**Circuit breakers.** Consecutive job failures trip a breaker per
session AND per query fingerprint (the FugueSQL DAG's deterministic
workflow uuid — built from task uuids, so the same query text over the
same session tables maps to the same key across submissions and across
restarts). An OPEN breaker answers immediately with a structured error
instead of burning engine time on a poison query; after the cooldown it
HALF-OPENs for exactly one probe — success closes it, failure re-opens
the cooldown window.

**Heartbeat watchdog.** Running jobs beat at execution milestones AND
on every cooperative cancellation check the inner workflow makes (the
job's CancelToken ``on_poll`` hook) — so a long multi-task query keeps
beating between device dispatches, and the timeout bounds the longest
SINGLE wedged dispatch, not total query duration. The supervisor tick
abandons any running job whose heartbeat is older than
``fugue.serve.heartbeat_timeout`` (belt over the runner's per-job
wall-clock timeout braces — a wedged XLA dispatch stops blocking
pollers even when the job was submitted without a timeout). The
tick also sweeps expired sessions (chaos site ``serve.sweep``) and runs
the job-payload TTL GC.
"""

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from fugue_tpu.testing.locktrace import tracked_lock

HEALTHY = "healthy"
DRAINING = "draining"
STOPPED = "stopped"

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# registry bound: a daemon serving millions of distinct queries must not
# keep one breaker object per fingerprint forever (closed failure-free
# breakers are stateless and rebuildable on demand)
_MAX_BREAKERS = 4096


class AdmissionError(Exception):
    """A submission the daemon refuses to accept right now. Carries the
    HTTP status and a Retry-After hint; the HTTP layer turns it into a
    structured payload + ``Retry-After`` header, and the fault
    classifier treats any error carrying ``retry_after`` as TRANSIENT
    (so client-side retry layers back off and try again)."""

    def __init__(self, message: str, status: int = 503,
                 retry_after: float = 1.0):
        super().__init__(message)
        self.status = int(status)
        self.retry_after = max(0.0, float(retry_after))


class BackpressureError(AdmissionError):
    """Overload rejection (queue depth / memory pressure / drain)."""


class SessionBusyError(AdmissionError):
    """Per-session concurrent-job cap hit (HTTP 429)."""

    def __init__(self, message: str, retry_after: float = 1.0):
        super().__init__(message, status=429, retry_after=retry_after)


class CircuitOpenError(AdmissionError):
    """A tripped breaker is refusing this session/query (HTTP 503 with
    the remaining cooldown as Retry-After)."""


class PoisonQueryError(AdmissionError):
    """A query fingerprint quarantined by its breaker: the same DAG
    failed ``threshold`` consecutive times, so the job settles with this
    structured error instead of executing again. Raised at execution
    start (the fingerprint needs the compiled DAG), so it reaches the
    client as the JOB's error payload — not as an HTTP rejection."""

    def __init__(self, message: str, retry_after: float = 0.0):
        super().__init__(message, status=422, retry_after=retry_after)


class CircuitBreaker:
    """One consecutive-failure breaker. Caller holds no lock — the
    breaker locks itself (submissions and job completions race)."""

    def __init__(self, key: str, threshold: int, cooldown: float):
        self.key = key
        self.threshold = max(1, int(threshold))
        self.cooldown = max(0.0, float(cooldown))
        self.state = CLOSED
        self.failures = 0          # consecutive
        self.trips = 0
        self.opened_at = 0.0
        self._probing = False      # one probe in flight while HALF_OPEN
        self._lock = tracked_lock("serve.supervisor.CircuitBreaker._lock")

    def allow(self) -> None:
        """Raise when the breaker refuses this attempt; admit (and claim
        the half-open probe slot) otherwise."""
        with self._lock:
            if self.state == CLOSED:
                return
            elapsed = time.monotonic() - self.opened_at
            if self.state == OPEN and elapsed >= self.cooldown:
                self.state = HALF_OPEN
                self._probing = False
            if self.state == HALF_OPEN and not self._probing:
                self._probing = True  # exactly one probe through
                return
            remaining = max(0.0, self.cooldown - elapsed)
            raise CircuitOpenError(
                f"circuit breaker {self.key} is {self.state} after "
                f"{self.failures} consecutive failures; retry in "
                f"{remaining:.1f}s",
                retry_after=remaining if remaining > 0 else self.cooldown,
            )

    def record_success(self) -> None:
        with self._lock:
            self.state = CLOSED
            self.failures = 0
            self._probing = False

    def release_probe(self) -> None:
        """A claimed half-open probe slot whose attempt produced NO
        verdict (the probe job was cancelled, or its submission failed
        after admission) goes back: neither success nor failure, but the
        next attempt must be allowed to probe — otherwise the breaker
        stays half-open-and-busy forever."""
        with self._lock:
            if self.state == HALF_OPEN:
                self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.state == HALF_OPEN or self.failures >= self.threshold:
                if self.state != OPEN:
                    self.trips += 1
                self.state = OPEN
                self.opened_at = time.monotonic()
                self._probing = False

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "key": self.key,
                "state": self.state,
                "consecutive_failures": self.failures,
                "trips": self.trips,
            }


class HealthState:
    """The daemon's one-way lifecycle state with drain bookkeeping."""

    def __init__(self) -> None:
        self._lock = tracked_lock("serve.supervisor.HealthState._lock")
        self.state = HEALTHY
        self.since = time.time()
        self.drain_deadline: Optional[float] = None  # monotonic

    def transition(self, state: str) -> None:
        with self._lock:
            self.state = state
            self.since = time.time()

    def start_drain(self, timeout: float) -> float:
        with self._lock:
            self.state = DRAINING
            self.since = time.time()
            self.drain_deadline = time.monotonic() + max(0.0, timeout)
            return self.drain_deadline

    @property
    def healthy(self) -> bool:
        return self.state == HEALTHY

    def drain_remaining(self) -> float:
        with self._lock:
            if self.drain_deadline is None:
                return 0.0
            return max(0.0, self.drain_deadline - time.monotonic())

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {"state": self.state, "since": self.since}
            if self.state == DRAINING and self.drain_deadline is not None:
                out["drain_remaining_seconds"] = round(
                    max(0.0, self.drain_deadline - time.monotonic()), 3
                )
            return out


class EngineSupervisor:
    """Breaker registry + heartbeat watchdog thread. ``tick_hooks`` are
    extra periodic maintenance callables (session sweep, job GC) the
    daemon registers; each runs isolated — one failing hook never stops
    the watchdog."""

    def __init__(
        self,
        threshold: int,
        cooldown: float,
        heartbeat_timeout: float = 0.0,
        log: Any = None,
    ):
        self.threshold = max(0, int(threshold))
        self.cooldown = max(0.0, float(cooldown))
        self.heartbeat_timeout = max(0.0, float(heartbeat_timeout))
        self._log = log
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._lock = tracked_lock("serve.supervisor.EngineSupervisor._lock")
        self.wedged_jobs = 0
        self._abandon: Optional[Callable[[Any], bool]] = None
        self._running_jobs: Callable[[], List[Any]] = list
        self.tick_hooks: List[Callable[[], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ---- breakers --------------------------------------------------------
    def _breaker(self, key: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                if len(self._breakers) >= _MAX_BREAKERS:
                    self._evict_locked()
                br = self._breakers[key] = CircuitBreaker(
                    key, self.threshold, self.cooldown
                )
            return br

    def _evict_locked(self) -> None:
        """Bound the registry on a long-lived daemon: drop the oldest
        CLOSED, failure-free breakers (insertion order) — they carry no
        state worth keeping. Tripped/half-open/failing breakers are
        never evicted."""
        for key in list(self._breakers):
            br = self._breakers[key]
            if br.state == CLOSED and br.failures == 0:
                del self._breakers[key]
                if len(self._breakers) < _MAX_BREAKERS // 2:
                    return

    def admit_session(self, session_id: str) -> None:
        # lookup-only: a breaker that was never tripped by a failure is
        # trivially closed, and the admission hot path must not allocate
        # registry entries per request
        if self.threshold <= 0:
            return
        with self._lock:
            br = self._breakers.get(f"session:{session_id}")
        if br is not None:
            br.allow()

    def admit_query(self, fingerprint: str) -> None:
        """Raises :class:`PoisonQueryError` for a quarantined query
        fingerprint (structured) — checked at execution start, right
        after the DAG (and so its deterministic uuid) exists."""
        if self.threshold <= 0:
            return
        with self._lock:
            br = self._breakers.get(f"query:{fingerprint}")
        if br is None:
            return
        try:
            br.allow()
        except CircuitOpenError as ex:
            raise PoisonQueryError(
                f"query {fingerprint[:12]} is quarantined after "
                f"{br.failures} consecutive failures; half-open probe in "
                f"{ex.retry_after:.1f}s",
                retry_after=ex.retry_after,
            ) from None

    def note_result(
        self, session_id: str, fingerprint: Optional[str], failed: bool
    ) -> None:
        if self.threshold <= 0:
            return
        for key in self._keys(session_id, fingerprint):
            if failed:
                self._breaker(key).record_failure()
            else:
                # successes only touch EXISTING breakers: allocating one
                # per distinct healthy query fingerprint would grow the
                # registry without bound on a long-lived daemon
                with self._lock:
                    br = self._breakers.get(key)
                if br is not None:
                    br.record_success()

    def note_cancelled(
        self, session_id: str, fingerprint: Optional[str]
    ) -> None:
        """A cancelled job is verdict-free for its breakers — but it may
        have been holding a half-open probe slot, which must go back so
        the quarantine can still be probed out of."""
        if self.threshold <= 0:
            return
        for key in self._keys(session_id, fingerprint):
            with self._lock:
                br = self._breakers.get(key)
            if br is not None:
                br.release_probe()

    def _keys(
        self, session_id: str, fingerprint: Optional[str]
    ) -> List[str]:
        keys = [f"session:{session_id}"]
        if fingerprint:
            keys.append(f"query:{fingerprint}")
        return keys

    def breaker_state_counts(self) -> Dict[str, int]:
        """How many breakers sit in each state right now — the labeled
        gauge (``fugue_serve_breaker_states{state=...}``) the daemon's
        scrape-time collector publishes."""
        with self._lock:
            breakers = list(self._breakers.values())
        out = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        for b in breakers:
            out[b.state] = out.get(b.state, 0) + 1
        return out

    def breaker_stats(self) -> Dict[str, Any]:
        with self._lock:
            breakers = list(self._breakers.values())
        tripped = [b.describe() for b in breakers if b.state != CLOSED]
        return {
            "enabled": self.threshold > 0,
            "threshold": self.threshold,
            "cooldown": self.cooldown,
            "total": len(breakers),
            "open": tripped,
            "trips": sum(b.trips for b in breakers),
        }

    # ---- heartbeat watchdog ----------------------------------------------
    def start(
        self,
        running_jobs: Callable[[], List[Any]],
        abandon: Optional[Callable[[Any], bool]] = None,
    ) -> None:
        """Start the watchdog thread; ``running_jobs`` snapshots the
        scheduler's RUNNING jobs and ``abandon`` (the scheduler's
        ``abandon``) terminalizes a wedged one — pollers unblock
        immediately instead of waiting out the stuck dispatch. Without
        it the watchdog only cancels the job's token."""
        if self._thread is not None:
            return
        self._running_jobs = running_jobs
        self._abandon = abandon
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="fugue-serve-supervisor"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5)

    def _interval(self) -> float:
        if self.heartbeat_timeout > 0:
            return max(0.05, min(0.25, self.heartbeat_timeout / 4.0))
        return 0.25

    def _watch(self) -> None:
        while not self._stop.wait(self._interval()):
            self.tick()

    def tick(self) -> None:
        """One maintenance pass (also callable directly from tests)."""
        if self.heartbeat_timeout > 0:
            for job in self._running_jobs():
                age = job.heartbeat_age
                if age is not None and age > self.heartbeat_timeout:
                    self.wedged_jobs += 1
                    if self._log is not None:
                        self._log.warning(
                            "fugue_tpu serve: job %s heartbeat is %.2fs "
                            "old (> %.2fs); cancelling as wedged",
                            job.job_id, age, self.heartbeat_timeout,
                        )
                    if self._abandon is not None:
                        self._abandon(job)
                    else:
                        job.token.cancel()
        for hook in list(self.tick_hooks):
            try:
                hook()
            except Exception as ex:  # one bad hook never stops the watchdog
                if self._log is not None:
                    self._log.warning(
                        "fugue_tpu serve: supervisor hook failed: %s: %s",
                        type(ex).__name__, ex,
                    )
