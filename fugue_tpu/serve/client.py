"""Minimal stdlib JSON client for the serving daemon's HTTP API — what
the integration tests and the sustained-throughput bench drive; the same
flow works from ``curl`` (see README "Serving")."""

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional


class ServeAPIError(RuntimeError):
    """A structured error answer from the daemon."""

    def __init__(self, status: int, error: Dict[str, Any]):
        self.status = status
        self.error = dict(error or {})
        super().__init__(
            f"HTTP {status}: {self.error.get('error')}: "
            f"{self.error.get('message')}"
        )


class ServeClient:
    def __init__(self, host: str, port: int, timeout: float = 120.0):
        self._base = f"http://{host}:{port}"
        self._timeout = timeout

    def _call(
        self,
        method: str,
        path: str,
        payload: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        data = (
            json.dumps(payload).encode("utf-8")
            if payload is not None
            else None
        )
        req = urllib.request.Request(
            self._base + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as ex:
            try:
                body = json.loads(ex.read().decode("utf-8"))
            except Exception:
                body = {}
            raise ServeAPIError(
                ex.code, body.get("error") or {"error": str(ex)}
            ) from None

    # ---- sessions --------------------------------------------------------
    def create_session(self, ttl: Optional[float] = None) -> str:
        payload: Dict[str, Any] = {} if ttl is None else {"ttl": ttl}
        return self._call("POST", "/v1/sessions", payload)["session_id"]

    def close_session(self, session_id: str) -> Dict[str, Any]:
        return self._call("POST", f"/v1/sessions/{session_id}/close", {})

    def session(self, session_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/v1/sessions/{session_id}")

    # ---- submissions -----------------------------------------------------
    def sql(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
    ) -> Dict[str, Any]:
        """Synchronous submit: returns the finished job snapshot (its
        ``result`` carries columns/rows when the script ends in a
        dataframe and ``collect`` is on)."""
        payload: Dict[str, Any] = {
            "sql": sql,
            "mode": "sync",
            "timeout": timeout,
            "collect": collect,
            "limit": limit,
        }
        if save_as is not None:
            payload["save_as"] = save_as
        return self._call("POST", f"/v1/sessions/{session_id}/sql", payload)

    def submit_async(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
    ) -> str:
        payload: Dict[str, Any] = {
            "sql": sql,
            "mode": "async",
            "timeout": timeout,
            "collect": collect,
            "limit": limit,
        }
        if save_as is not None:
            payload["save_as"] = save_as
        return self._call(
            "POST", f"/v1/sessions/{session_id}/sql", payload
        )["job_id"]

    def job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/v1/jobs/{job_id}")

    def cancel(self, job_id: str) -> Dict[str, Any]:
        return self._call("POST", f"/v1/jobs/{job_id}/cancel", {})

    def wait(self, job_id: str, poll: float = 0.05) -> Dict[str, Any]:
        """Poll an async job until it finishes; returns the snapshot."""
        import time

        while True:
            snap = self.job(job_id)
            if snap["status"] in ("done", "error", "cancelled"):
                return snap
            time.sleep(poll)

    # ---- daemon ----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        return self._call("GET", "/v1/status")

    def health(self) -> bool:
        return bool(self._call("GET", "/v1/health").get("ok"))
