"""Minimal stdlib JSON client for the serving daemon's HTTP API — what
the integration tests and the sustained-throughput bench drive; the same
flow works from ``curl`` (see README "Serving").

**Transient retry** (ISSUE 7): every call retries bounded-exponential on
transient transport failures — connection refused/reset while a daemon
restarts, and the daemon's own 503/429 backpressure answers — reusing
the workflow fault classifier's triage through
:func:`fugue_tpu.rpc.http._is_transient_transport_error` and honoring
the server's ``Retry-After`` header over the local backoff schedule.
Deterministic failures (404s, structured job errors, 400s) fail fast.
The budget comes from ``fugue.serve.client.retries`` (the registered
default; per-client override via the ``retries`` argument).

**Multi-endpoint failover** (ISSUE 13): the client accepts a LIST of
``(host, port)`` endpoints — a fleet's replicas, or its router plus a
fallback — and ROTATES to the next endpoint instead of re-hammering one
when an attempt dies on the transport (connection refused/reset: the
endpoint is gone or restarting) or answers 503 (draining replica,
backpressure — another replica may have headroom). 429 stays on the
same endpoint: a per-session cap follows the session wherever it lives.
The rotation spends the SAME bounded retry budget and still honors
``Retry-After``; a single-endpoint client behaves exactly as before.

Retries are **at-least-once**: a connection that dies after the request
was sent may replay a submission — and a failed-over submit may land on
a replica that adopts the job the first replica already journaled. The
daemon's saves are overwrite-mode idempotent and job ids are stable
across failover, so duplicates converge; set ``retries=0`` for flows
where a duplicate submit is worse than a failed call.
"""

import json
import random
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from fugue_tpu.constants import (
    FUGUE_CONF_SERVE_CLIENT_RETRIES,
    FUGUE_CONF_SERVE_SYNC_WAIT,
    conf_default,
)
from fugue_tpu.rpc.http import (
    _is_transient_transport_error,
    backoff_delay,
    parse_retry_after,
)

_TERMINAL = ("done", "error", "cancelled")


class ServeAPIError(RuntimeError):
    """A structured error answer from the daemon. ``retry_after`` is the
    server's backoff hint on 503/429 backpressure rejections (None on
    deterministic errors) — the fault classifier treats an exception
    carrying ``retry_after`` as TRANSIENT."""

    def __init__(
        self,
        status: int,
        error: Dict[str, Any],
        retry_after: Optional[float] = None,
    ):
        self.status = status
        self.error = dict(error or {})
        self.retry_after = retry_after
        super().__init__(
            f"HTTP {status}: {self.error.get('error')}: "
            f"{self.error.get('message')}"
        )


class ServeJobTimeoutError(TimeoutError):
    """:meth:`ServeClient.wait` gave up on a job that did not reach a
    terminal state within its deadline. Structured: carries the job id,
    the deadline, and the job's last observed snapshot (still
    queued/running), so a caller can keep polling, cancel, or alert —
    instead of hanging forever on a lost job id."""

    def __init__(
        self,
        job_id: str,
        deadline: float,
        last_snapshot: Optional[Dict[str, Any]] = None,
    ):
        self.job_id = job_id
        self.deadline = deadline
        self.last_snapshot = dict(last_snapshot or {})
        status = self.last_snapshot.get("status", "unknown")
        super().__init__(
            f"job {job_id} did not finish within {deadline:.1f}s "
            f"(last status: {status})"
        )


EndpointArg = Union[str, Sequence[Tuple[str, int]]]


class ServeClient:
    """``ServeClient(host, port)`` talks to one daemon (or a fleet
    router); ``ServeClient([(h1, p1), (h2, p2)])`` failovers across
    endpoints (see module docstring for the rotation + at-least-once
    semantics)."""

    def __init__(
        self,
        host: EndpointArg,
        port: Optional[int] = None,
        timeout: float = 120.0,
        retries: Optional[int] = None,
    ):
        if isinstance(host, (list, tuple)) and port is None:
            endpoints = [(str(h), int(p)) for h, p in host]
            if not endpoints:
                raise ValueError("endpoint list must not be empty")
        else:
            if port is None:
                raise ValueError("port is required with a single host")
            endpoints = [(str(host), int(port))]
        self._endpoints: List[Tuple[str, int]] = endpoints
        self._current = 0
        self._timeout = timeout
        self._retries = max(
            0,
            int(
                conf_default(FUGUE_CONF_SERVE_CLIENT_RETRIES)
                if retries is None
                else retries
            ),
        )

    @property
    def endpoints(self) -> List[Tuple[str, int]]:
        return list(self._endpoints)

    @property
    def current_endpoint(self) -> Tuple[str, int]:
        return self._endpoints[self._current]

    def _rotate(self) -> None:
        self._current = (self._current + 1) % len(self._endpoints)

    def _call(
        self,
        method: str,
        path: str,
        payload: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        rng = random.Random()
        attempt = 0
        start = self._current
        while True:
            attempt += 1
            try:
                return self._call_once(method, path, payload)
            except Exception as ex:
                status = ex.status if isinstance(ex, ServeAPIError) else None
                transient = (
                    status in (503, 429)
                    if status is not None
                    else _is_transient_transport_error(ex)
                )
                # a 404 AFTER a rotation is usually the WRONG REPLICA
                # (the session lives elsewhere), not a verdict: keep
                # rotating through the budget instead of fail-fasting
                # on — and then sticking to — a replica that never
                # owned the session
                wrong_replica = status == 404 and self._current != start
                if attempt > self._retries or not (
                    transient or wrong_replica
                ):
                    if self._current != start and status == 404:
                        # never WEDGE on a foreign replica: later calls
                        # should start from the session's last-good one
                        self._current = start
                    raise
                # failover rotation: a transport death, a 503 (drain,
                # backpressure) or a wrong-replica 404 sends the next
                # attempt to the next endpoint; 429 (per-session cap)
                # retries in place — the session's jobs live on one
                # replica regardless
                if len(self._endpoints) > 1 and status != 429:
                    self._rotate()
                # retry_after is already parse_retry_after-capped
                time.sleep(
                    backoff_delay(
                        attempt, rng, getattr(ex, "retry_after", None)
                    )
                )

    def _call_once(
        self,
        method: str,
        path: str,
        payload: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        data = (
            json.dumps(payload).encode("utf-8")
            if payload is not None
            else None
        )
        host, port = self._endpoints[self._current]
        req = urllib.request.Request(
            f"http://{host}:{port}" + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as ex:
            try:
                body = json.loads(ex.read().decode("utf-8"))
            except Exception:
                body = {}
            raise ServeAPIError(
                ex.code,
                body.get("error") or {"error": str(ex)},
                retry_after=parse_retry_after(ex.headers),
            ) from None

    # ---- sessions --------------------------------------------------------
    def create_session(self, ttl: Optional[float] = None) -> str:
        payload: Dict[str, Any] = {} if ttl is None else {"ttl": ttl}
        return self._call("POST", "/v1/sessions", payload)["session_id"]

    def close_session(self, session_id: str) -> Dict[str, Any]:
        return self._call("POST", f"/v1/sessions/{session_id}/close", {})

    def session(self, session_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/v1/sessions/{session_id}")

    # ---- submissions -----------------------------------------------------
    def sql(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
        priority: int = 0,
        deadline: float = 0.0,
    ) -> Dict[str, Any]:
        """Synchronous submit: returns the finished job snapshot (its
        ``result`` carries columns/rows when the script ends in a
        dataframe and ``collect`` is on). Under deep queues the daemon
        may degrade the submit to async (202 + ``degraded_to_async``):
        this helper then polls the job to completion, so callers keep
        sync semantics either way.

        ``priority`` (higher runs first under the predictive scheduler,
        and high-priority work survives overload shedding longest) and
        ``deadline`` (relative seconds; a job still queued past it
        settles as a structured DeadlineExceededError instead of
        running) are ISSUE 18 admission fields."""
        payload: Dict[str, Any] = {
            "sql": sql,
            "mode": "sync",
            "timeout": timeout,
            "collect": collect,
            "limit": limit,
        }
        if priority:
            payload["priority"] = int(priority)
        if deadline > 0:
            payload["deadline"] = float(deadline)
        if save_as is not None:
            payload["save_as"] = save_as
        snap = self._call(
            "POST", f"/v1/sessions/{session_id}/sql", payload
        )
        if snap.get("degraded_to_async"):
            return self.wait(snap["job_id"])
        return snap

    def submit_async(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
        priority: int = 0,
        deadline: float = 0.0,
    ) -> str:
        payload: Dict[str, Any] = {
            "sql": sql,
            "mode": "async",
            "timeout": timeout,
            "collect": collect,
            "limit": limit,
        }
        if priority:
            payload["priority"] = int(priority)
        if deadline > 0:
            payload["deadline"] = float(deadline)
        if save_as is not None:
            payload["save_as"] = save_as
        return self._call(
            "POST", f"/v1/sessions/{session_id}/sql", payload
        )["job_id"]

    def job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/v1/jobs/{job_id}")

    def cancel(self, job_id: str) -> Dict[str, Any]:
        return self._call("POST", f"/v1/jobs/{job_id}/cancel", {})

    def wait(
        self,
        job_id: str,
        poll: float = 0.05,
        deadline: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Poll an async job until it finishes; returns the snapshot.

        ``deadline`` bounds the total wait in seconds — on expiry a
        structured :class:`ServeJobTimeoutError` (job id + last
        snapshot) is raised, so a lost job id can never hang the caller.
        None takes the registered ``fugue.serve.sync_wait`` default (the
        same budget the daemon gives a sync submit); <= 0 waits
        forever (the old behavior, explicit opt-in only)."""
        limit = float(
            conf_default(FUGUE_CONF_SERVE_SYNC_WAIT)
            if deadline is None
            else deadline
        )
        start = time.monotonic()
        snap: Dict[str, Any] = {}
        while True:
            snap = self.job(job_id)
            if snap["status"] in _TERMINAL:
                return snap
            if limit > 0 and time.monotonic() - start >= limit:
                raise ServeJobTimeoutError(job_id, limit, snap)
            time.sleep(poll)

    # ---- standing pipelines / materialized views -------------------------
    def register_pipeline(
        self, session_id: str, spec: Dict[str, Any], step: bool = True
    ) -> Dict[str, Any]:
        """Register a standing pipeline maintaining ``spec["name"]`` as
        this session's materialized view (see README "Continuous
        pipelines" for the spec shape)."""
        payload = dict(spec)
        payload["step"] = step
        return self._call(
            "POST", f"/v1/sessions/{session_id}/pipelines", payload
        )

    def pipelines(self, session_id: str) -> List[Dict[str, Any]]:
        return self._call(
            "GET", f"/v1/sessions/{session_id}/pipelines"
        )["pipelines"]

    def pipeline(self, session_id: str, name: str) -> Dict[str, Any]:
        return self._call(
            "GET", f"/v1/sessions/{session_id}/pipelines/{name}"
        )

    def step_pipeline(
        self, session_id: str, name: str, force_refresh: bool = False
    ) -> Dict[str, Any]:
        """Run one micro-batch now; ``{"skipped": "busy"}`` when a
        concurrent (ticker or manual) step already runs."""
        return self._call(
            "POST",
            f"/v1/sessions/{session_id}/pipelines/{name}/step",
            {"force_refresh": force_refresh},
        )

    def remove_pipeline(
        self, session_id: str, name: str, drop_table: bool = False
    ) -> Dict[str, Any]:
        return self._call(
            "DELETE",
            f"/v1/sessions/{session_id}/pipelines/{name}",
            {"drop_table": drop_table},
        )

    # ---- daemon ----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        return self._call("GET", "/v1/status")

    def health(self) -> bool:
        return bool(self._call("GET", "/v1/health").get("ok"))
