"""Minimal stdlib JSON client for the serving daemon's HTTP API — what
the integration tests and the sustained-throughput bench drive; the same
flow works from ``curl`` (see README "Serving").

**Transient retry** (ISSUE 7): every call retries bounded-exponential on
transient transport failures — connection refused/reset while a daemon
restarts, and the daemon's own 503/429 backpressure answers — reusing
the workflow fault classifier's triage through
:func:`fugue_tpu.rpc.http._is_transient_transport_error` and honoring
the server's ``Retry-After`` header over the local backoff schedule.
Deterministic failures (404s, structured job errors, 400s) fail fast.
The budget comes from ``fugue.serve.client.retries`` (the registered
default; per-client override via the ``retries`` argument). Retries are
at-least-once: a connection that dies after the request was sent may
replay a submission — the daemon's saves are overwrite-mode idempotent,
but set ``retries=0`` for flows where a duplicate submit is worse than
a failed call.
"""

import json
import random
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from fugue_tpu.constants import FUGUE_CONF_SERVE_CLIENT_RETRIES, conf_default
from fugue_tpu.rpc.http import (
    _is_transient_transport_error,
    backoff_delay,
    parse_retry_after,
)


class ServeAPIError(RuntimeError):
    """A structured error answer from the daemon. ``retry_after`` is the
    server's backoff hint on 503/429 backpressure rejections (None on
    deterministic errors) — the fault classifier treats an exception
    carrying ``retry_after`` as TRANSIENT."""

    def __init__(
        self,
        status: int,
        error: Dict[str, Any],
        retry_after: Optional[float] = None,
    ):
        self.status = status
        self.error = dict(error or {})
        self.retry_after = retry_after
        super().__init__(
            f"HTTP {status}: {self.error.get('error')}: "
            f"{self.error.get('message')}"
        )


class ServeClient:
    def __init__(
        self,
        host: str,
        port: int,
        timeout: float = 120.0,
        retries: Optional[int] = None,
    ):
        self._base = f"http://{host}:{port}"
        self._timeout = timeout
        self._retries = max(
            0,
            int(
                conf_default(FUGUE_CONF_SERVE_CLIENT_RETRIES)
                if retries is None
                else retries
            ),
        )

    def _call(
        self,
        method: str,
        path: str,
        payload: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        rng = random.Random()
        attempt = 0
        while True:
            attempt += 1
            try:
                return self._call_once(method, path, payload)
            except Exception as ex:
                transient = (
                    ex.status in (503, 429)
                    if isinstance(ex, ServeAPIError)
                    else _is_transient_transport_error(ex)
                )
                if attempt > self._retries or not transient:
                    raise
                # retry_after is already parse_retry_after-capped
                time.sleep(
                    backoff_delay(
                        attempt, rng, getattr(ex, "retry_after", None)
                    )
                )

    def _call_once(
        self,
        method: str,
        path: str,
        payload: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        data = (
            json.dumps(payload).encode("utf-8")
            if payload is not None
            else None
        )
        req = urllib.request.Request(
            self._base + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as resp:
                return json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as ex:
            try:
                body = json.loads(ex.read().decode("utf-8"))
            except Exception:
                body = {}
            raise ServeAPIError(
                ex.code,
                body.get("error") or {"error": str(ex)},
                retry_after=parse_retry_after(ex.headers),
            ) from None

    # ---- sessions --------------------------------------------------------
    def create_session(self, ttl: Optional[float] = None) -> str:
        payload: Dict[str, Any] = {} if ttl is None else {"ttl": ttl}
        return self._call("POST", "/v1/sessions", payload)["session_id"]

    def close_session(self, session_id: str) -> Dict[str, Any]:
        return self._call("POST", f"/v1/sessions/{session_id}/close", {})

    def session(self, session_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/v1/sessions/{session_id}")

    # ---- submissions -----------------------------------------------------
    def sql(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
    ) -> Dict[str, Any]:
        """Synchronous submit: returns the finished job snapshot (its
        ``result`` carries columns/rows when the script ends in a
        dataframe and ``collect`` is on). Under deep queues the daemon
        may degrade the submit to async (202 + ``degraded_to_async``):
        this helper then polls the job to completion, so callers keep
        sync semantics either way."""
        payload: Dict[str, Any] = {
            "sql": sql,
            "mode": "sync",
            "timeout": timeout,
            "collect": collect,
            "limit": limit,
        }
        if save_as is not None:
            payload["save_as"] = save_as
        snap = self._call(
            "POST", f"/v1/sessions/{session_id}/sql", payload
        )
        if snap.get("degraded_to_async"):
            return self.wait(snap["job_id"])
        return snap

    def submit_async(
        self,
        session_id: str,
        sql: str,
        save_as: Optional[str] = None,
        timeout: float = 0.0,
        collect: bool = True,
        limit: int = 10_000,
    ) -> str:
        payload: Dict[str, Any] = {
            "sql": sql,
            "mode": "async",
            "timeout": timeout,
            "collect": collect,
            "limit": limit,
        }
        if save_as is not None:
            payload["save_as"] = save_as
        return self._call(
            "POST", f"/v1/sessions/{session_id}/sql", payload
        )["job_id"]

    def job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/v1/jobs/{job_id}")

    def cancel(self, job_id: str) -> Dict[str, Any]:
        return self._call("POST", f"/v1/jobs/{job_id}/cancel", {})

    def wait(self, job_id: str, poll: float = 0.05) -> Dict[str, Any]:
        """Poll an async job until it finishes; returns the snapshot."""
        while True:
            snap = self.job(job_id)
            if snap["status"] in ("done", "error", "cancelled"):
                return snap
            time.sleep(poll)

    # ---- daemon ----------------------------------------------------------
    def status(self) -> Dict[str, Any]:
        return self._call("GET", "/v1/status")

    def health(self) -> bool:
        return bool(self._call("GET", "/v1/health").get("ok"))
