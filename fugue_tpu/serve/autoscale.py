"""Fleet autoscaling (ISSUE 18): sustained-pressure scale-up, idle
drain-then-retire scale-down, on top of the PR 13 fleet.

:class:`FleetAutoscaler` is a small control loop over an in-process
:class:`~fugue_tpu.serve.fleet.ServeFleet`. Every
``fugue.serve.autoscale.interval`` seconds it samples each replica's
scheduler (queue depth, running jobs) and — when
``fugue.serve.autoscale.scale_up_p99_ms`` is set — the p99 of the
``fugue_serve_job_seconds`` histogram *delta* since the previous tick,
then decides:

- **scale up** when the mean backlog per replica has been at or above
  ``scale_up_queue`` (or the tick-window p99 above ``scale_up_p99_ms``)
  for ``sustain_ticks`` consecutive samples and the fleet is below
  ``max_replicas``. Sustained pressure, not a spike: a one-tick burst
  that the queue absorbs is exactly what the queue is for.
- **scale down** when the whole fleet has been completely idle (zero
  queued, zero running) for ``idle_ticks`` consecutive samples and the
  fleet is above ``min_replicas``. The retired replica is the
  newest-added one, via :meth:`~fugue_tpu.serve.fleet.ServeFleet.
  retire_replica` — drain → planned journal adoption → verify-empty →
  detach, i.e. the SAME provably-loss-free move as a rolling restart,
  which is why a hard kill at chaos site ``serve.scale`` mid-retire
  degrades to an ordinary death failover instead of losing sessions.

- **replace degraded** (ISSUE 19): a replica whose engine lost a device
  (``daemon._engine.is_degraded`` — the ``degraded`` /v1/health state)
  counts as sustained pressure immediately. The controller first spawns
  a healthy replacement (when the healthy count is below the floor and
  the fleet below ``max_replicas``), then drain-retires the degraded
  replica through the same loss-free retire as a rolling restart — its
  sessions adopt onto the healthy survivors, zero session loss.
  Ordinary idle scale-down also prefers a degraded replica over the
  newest-added one.

Each action starts a ``cooldown`` window during which no further action
fires, so a scale-up's effect on the backlog is observed before the
next decision (classic anti-flap hysteresis).

The loop never raises: a failed action (e.g. a transient
no-survivor-available retire) is counted on
``fugue_autoscale_errors_total`` and retried on a later tick. Decisions
are also exposed synchronously via :meth:`tick` so tests and the bench
drive the controller deterministically without the wall-clock thread.
"""

import threading
import time
from typing import Any, Dict, List, Optional

from fugue_tpu.constants import (
    FUGUE_CONF_SERVE_AUTOSCALE_COOLDOWN,
    FUGUE_CONF_SERVE_AUTOSCALE_IDLE_TICKS,
    FUGUE_CONF_SERVE_AUTOSCALE_INTERVAL,
    FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS,
    FUGUE_CONF_SERVE_AUTOSCALE_MIN_REPLICAS,
    FUGUE_CONF_SERVE_AUTOSCALE_SUSTAIN_TICKS,
    FUGUE_CONF_SERVE_AUTOSCALE_UP_P99_MS,
    FUGUE_CONF_SERVE_AUTOSCALE_UP_QUEUE,
    typed_conf_get,
)
from fugue_tpu.obs import MetricsRegistry
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.utils.params import ParamDict

_JOB_HISTOGRAM = "fugue_serve_job_seconds"


class FleetAutoscaler:
    """Pressure-driven replica-count controller for a ServeFleet."""

    def __init__(self, fleet: Any, conf: Any = None):
        conf = ParamDict(conf)
        self._fleet = fleet
        self.max_replicas = max(
            1, int(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS))
        )
        self.min_replicas = max(
            1, int(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_MIN_REPLICAS))
        )
        self.interval = max(
            0.02, float(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_INTERVAL))
        )
        self.up_queue = max(
            1, int(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_UP_QUEUE))
        )
        # 0 = the p99 signal is OFF (queue pressure alone decides)
        self.up_p99_ms = max(
            0.0, float(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_UP_P99_MS))
        )
        self.sustain_ticks = max(
            1, int(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_SUSTAIN_TICKS))
        )
        self.idle_ticks = max(
            1, int(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_IDLE_TICKS))
        )
        self.cooldown = max(
            0.0, float(typed_conf_get(conf, FUGUE_CONF_SERVE_AUTOSCALE_COOLDOWN))
        )
        self._lock = tracked_lock("serve.autoscale.FleetAutoscaler._lock")
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._last_action_at = 0.0
        self._last_decision = "idle"
        # per-replica (count, sum-of-bucket-counts) snapshot of the job
        # histogram, so each tick's p99 covers only THAT tick's jobs
        self._hist_base: Dict[str, List[int]] = {}
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._metrics = MetricsRegistry()
        self._m_ups = self._metrics.counter(
            "fugue_autoscale_scale_ups_total", "replicas added by the autoscaler"
        )
        self._m_downs = self._metrics.counter(
            "fugue_autoscale_scale_downs_total",
            "replicas drained and retired by the autoscaler",
        )
        self._m_errors = self._metrics.counter(
            "fugue_autoscale_errors_total",
            "autoscale actions that failed and will retry",
        )
        self._m_ticks = self._metrics.counter(
            "fugue_autoscale_ticks_total", "control-loop samples taken"
        )
        self._metrics.add_collector(self._collect_gauges)

    def _collect_gauges(self) -> None:
        self._metrics.gauge(
            "fugue_autoscale_replicas", "current fleet replica count"
        ).labels().set(len(self._fleet.replica_ids))
        with self._lock:
            pressure, idle = self._pressure_ticks, self._idle_ticks
        self._metrics.gauge(
            "fugue_autoscale_pressure_ticks",
            "consecutive ticks at or above the scale-up threshold",
        ).labels().set(pressure)
        self._metrics.gauge(
            "fugue_autoscale_idle_ticks",
            "consecutive ticks with a completely idle fleet",
        ).labels().set(idle)

    # ---- lifecycle -------------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            return self
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fugue-fleet-autoscale"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_event.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)

    def _loop(self) -> None:
        while not self._stop_event.wait(self.interval):
            try:
                self.tick()
            except Exception:  # pragma: no cover - loop must survive
                self._m_errors.labels().inc()

    # ---- sampling --------------------------------------------------------
    def _sample(self) -> Dict[str, Any]:
        """One pass over the live replicas' schedulers (in-process: the
        autoscaler runs next to the fleet, not over HTTP)."""
        queued = running = 0
        p99_ms = 0.0
        degraded: List[str] = []
        rids = self._fleet.replica_ids
        for rid in rids:
            try:
                daemon = self._fleet.replica(rid)
                counts = daemon.scheduler.counts()
            except Exception:
                continue  # replica mid-restart/retire: skip this tick
            queued += int(counts.get("queued") or 0)
            running += int(counts.get("running") or 0)
            if getattr(daemon._engine, "is_degraded", False):
                degraded.append(rid)
            if self.up_p99_ms > 0.0:
                p99_ms = max(p99_ms, self._replica_p99_ms(rid, daemon))
        return {
            "replicas": len(rids),
            "queued": queued,
            "running": running,
            "backlog_per_replica": queued / max(1, len(rids)),
            "p99_ms": round(p99_ms, 3),
            "degraded": degraded,
        }

    def _replica_p99_ms(self, rid: str, daemon: Any) -> float:
        """p99 upper-bound estimate over the jobs THIS replica finished
        since the previous tick: the cumulative ``fugue_serve_job_seconds``
        buckets are snapshotted per tick and the delta's 99th-percentile
        bucket boundary is the estimate (Prometheus-style histogram
        quantile, but windowed tick-to-tick instead of scrape-to-scrape)."""
        try:
            family = daemon._engine.metrics.get(_JOB_HISTOGRAM)
        except Exception:
            return 0.0
        if family is None:
            return 0.0
        buckets: Optional[Any] = None
        counts: Optional[List[int]] = None
        for _, child in family.children():
            if buckets is None:
                buckets = child.buckets
                counts = [0] * len(child.buckets)
            with child._lock:
                for i, c in enumerate(child.counts):
                    counts[i] += c
        if buckets is None or counts is None:
            return 0.0
        base = self._hist_base.get(rid, [0] * len(counts))
        delta = [max(0, c - b) for c, b in zip(counts, base)]
        self._hist_base[rid] = counts
        total = sum(delta)
        if total == 0:
            return 0.0
        rank = total * 0.99
        seen = 0
        for i, c in enumerate(delta):
            seen += c
            if seen >= rank:
                b = buckets[i]
                return (b if b != float("inf") else buckets[-2] * 2) * 1000.0
        return buckets[-2] * 2 * 1000.0  # pragma: no cover

    # ---- control ---------------------------------------------------------
    def tick(self) -> str:
        """One sample + decision + (maybe) action. Returns the decision:
        ``scale_up``/``scale_down``/``pressure``/``idle``/``steady``/
        ``cooldown``/``error``."""
        self._m_ticks.labels().inc()
        sample = self._sample()
        degraded = sample.get("degraded") or []
        with self._lock:
            # a degraded replica (lost device, reduced mesh) IS
            # sustained pressure: its capacity won't come back on its own
            hot = (
                sample["backlog_per_replica"] >= self.up_queue
                or (
                    self.up_p99_ms > 0.0
                    and sample["p99_ms"] >= self.up_p99_ms
                )
                or len(degraded) > 0
            )
            cold = sample["queued"] == 0 and sample["running"] == 0
            self._pressure_ticks = self._pressure_ticks + 1 if hot else 0
            self._idle_ticks = self._idle_ticks + 1 if cold else 0
            n = sample["replicas"]
            in_cooldown = (
                self._last_action_at > 0.0
                and time.monotonic() - self._last_action_at < self.cooldown
            )
            want_up = (
                self._pressure_ticks >= self.sustain_ticks
                and n < self.max_replicas
            )
            want_down = (
                self._idle_ticks >= self.idle_ticks and n > self.min_replicas
            )
        if degraded:
            # replace-then-retire: first make sure enough HEALTHY
            # replicas exist to cover the floor, then drain-retire the
            # degraded one (loss-free: its sessions adopt onto the
            # survivors). The cooldown window paces the two steps.
            if in_cooldown:
                self._last_decision = "cooldown"
                return self._last_decision
            healthy = n - len(degraded)
            if healthy < self.min_replicas and n < self.max_replicas:
                self._last_decision = self._scale_up()
                return self._last_decision
            if healthy >= self.min_replicas:
                self._last_decision = self._retire_degraded(degraded[0])
                return self._last_decision
            # floor uncoverable (at max_replicas): keep the degraded
            # capacity rather than shrink below the operator's floor
            self._last_decision = "pressure"
            return self._last_decision
        if (want_up or want_down) and in_cooldown:
            self._last_decision = "cooldown"
            return self._last_decision
        if want_up:
            self._last_decision = self._scale_up()
        elif want_down:
            self._last_decision = self._scale_down()
        elif hot:
            self._last_decision = "pressure"
        elif cold:
            self._last_decision = "idle"
        else:
            self._last_decision = "steady"
        return self._last_decision

    def _scale_up(self) -> str:
        try:
            rid = self._fleet.add_replica()
        except Exception:
            self._m_errors.labels().inc()
            return "error"
        self._m_ups.labels().inc()
        with self._lock:
            self._pressure_ticks = 0
            self._last_action_at = time.monotonic()
        return f"scale_up {rid}"

    def _retire_degraded(self, rid: str) -> str:
        """Drain-then-retire a replica whose engine lost a device: its
        sessions adopt onto the healthy survivors (the same loss-free
        move as a rolling restart); the preceding scale-up restored the
        fleet's capacity."""
        try:
            self._fleet.retire_replica(rid)
        except Exception:
            self._m_errors.labels().inc()
            return "error"
        self._m_downs.labels().inc()
        with self._lock:
            self._last_action_at = time.monotonic()
        return f"retire_degraded {rid}"

    def _scale_down(self) -> str:
        # retire the NEWEST replica: boot-time slots (r0..rN-1 from
        # fugue.serve.fleet.replicas) are the floor the operator asked
        # for; autoscaled additions go first. A DEGRADED replica jumps
        # the queue — shrinking should shed the reduced-mesh capacity.
        rids = self._fleet.replica_ids
        if len(rids) <= 1:  # pragma: no cover - guarded by want_down
            return "steady"
        target = rids[-1]
        for rid in rids:
            try:
                daemon = self._fleet.replica(rid)
            except Exception:
                continue
            if getattr(daemon._engine, "is_degraded", False):
                target = rid
                break
        try:
            self._fleet.retire_replica(target)
        except Exception:
            self._m_errors.labels().inc()
            return "error"
        self._m_downs.labels().inc()
        with self._lock:
            self._idle_ticks = 0
            self._last_action_at = time.monotonic()
        return f"scale_down {target}"

    # ---- observability ---------------------------------------------------
    def render_metrics(self) -> str:
        return self._metrics.render()

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "max_replicas": self.max_replicas,
                "min_replicas": self.min_replicas,
                "interval": self.interval,
                "scale_up_queue": self.up_queue,
                "scale_up_p99_ms": self.up_p99_ms,
                "sustain_ticks": self.sustain_ticks,
                "idle_ticks": self.idle_ticks,
                "cooldown": self.cooldown,
                "pressure_ticks": self._pressure_ticks,
                "idle_ticks_now": self._idle_ticks,
                "last_decision": self._last_decision,
            }
        out["replicas"] = len(self._fleet.replica_ids)
        counters = self._metrics.get("fugue_autoscale_scale_ups_total")
        out["scale_ups"] = (
            int(sum(v for _, v in counters.as_dict().items()))
            if counters is not None
            else 0
        )
        counters = self._metrics.get("fugue_autoscale_scale_downs_total")
        out["scale_downs"] = (
            int(sum(v for _, v in counters.as_dict().items()))
            if counters is not None
            else 0
        )
        return out
