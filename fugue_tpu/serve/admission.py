"""Predictive admission (ISSUE 18): the cost model behind the
overload-survival plane.

The serving daemon already *observes* everything this module needs: the
runtime-statistics store (PR 14) keeps a ring of
:meth:`~fugue_tpu.obs.profile.RunProfile.observation` payloads per query
fingerprint — total wall milliseconds plus per-task device bytes — and
the memory governor (PR 4) publishes the device-byte budget. What was
missing is the *forward* direction: before a job runs, predict what it
will cost, and let the scheduler and admission controller plan against
the prediction instead of reacting to the damage.

:class:`QueryCostModel` turns a fingerprint's history into a
:class:`CostEstimate` (mean wall ms, max observed peak device bytes;
registered defaults for never-seen queries). Because a FugueSQL
submission's DAG fingerprint only exists *after* compilation in the
worker, the model also keeps a bounded SQL-text → fingerprint map fed
back by the execution path (:meth:`note_fingerprint`): the first run of
a query is costed at the defaults, every repeat is costed from its own
history — exactly the population (hot, repeated queries) where
prediction pays.

:class:`PredictiveAdmission` owns the live planning state on top of the
model:

- **in-flight predicted bytes** — the sum of running jobs' predicted
  peaks, maintained by the scheduler's start/finish hooks; a queued
  job whose prediction would overflow
  ``fugue.serve.admission.memory_fraction`` of the governed budget
  waits for headroom instead of starting (and instead of the daemon
  rejecting it on *observed* fill);
- **predicted drain seconds** — backlog cost over worker slots, the
  quantity the daemon sheds on (503 + ``Retry-After`` sized from it)
  and the number a 503's ``Retry-After`` header carries, so clients
  back off for as long as the queue is actually predicted to take.

Everything here is advisory arithmetic under one small lock
(``serve.admission.PredictiveAdmission._lock`` in the canonical order,
just above the scheduler's): no filesystem access, no blocking calls —
the stats store reads its snapshots from memory and refreshes from disk
on its own cadence.
"""

from typing import Any, Dict, NamedTuple, Optional

from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.utils.hash import to_uuid

# the sql-key → fingerprint feedback map is bounded: serving vocabulary
# is finite (hot queries repeat), and an unbounded map would leak under
# adversarial one-shot SQL
_MAX_SQL_KEYS = 4096


def sql_cost_key(sql: str) -> str:
    """The submit-time identity of a query's *text* — what the cost
    model can know before compilation produces the DAG fingerprint.
    Whitespace-normalized so formatting differences share history."""
    return to_uuid("serve.admission", " ".join(str(sql).split()))


class CostEstimate(NamedTuple):
    """One job's predicted cost. ``observed`` distinguishes a real
    stats-store-backed estimate from the registered defaults."""

    wall_ms: float
    device_bytes: int
    observed: bool


class QueryCostModel:
    """Fingerprint → :class:`CostEstimate` from stats-store history.

    Stateless beyond the bounded sql-key map; safe to share between the
    daemon's admission path and the scheduler's pick loop."""

    def __init__(
        self,
        stats_store: Any = None,
        default_ms: float = 250.0,
        default_bytes: int = 32 * 1024 * 1024,
    ):
        self._stats = stats_store
        self.default_ms = max(1.0, float(default_ms))
        self.default_bytes = max(1, int(default_bytes))
        self._lock = tracked_lock("serve.admission.QueryCostModel._lock")
        self._sql_to_fp: Dict[str, str] = {}

    # ---- fingerprint feedback -------------------------------------------
    def note_fingerprint(self, sql_key: str, fingerprint: str) -> None:
        """Execution-path feedback: this SQL text compiled to this DAG
        fingerprint — the *next* submission of the same text is costed
        from the fingerprint's history."""
        if not sql_key or not fingerprint:
            return
        with self._lock:
            if (
                len(self._sql_to_fp) >= _MAX_SQL_KEYS
                and sql_key not in self._sql_to_fp
            ):
                # drop the oldest mapping (insertion order): the hot
                # vocabulary re-learns in one execution
                self._sql_to_fp.pop(next(iter(self._sql_to_fp)))
            self._sql_to_fp[sql_key] = fingerprint

    def resolve(self, sql_key: str) -> Optional[str]:
        with self._lock:
            return self._sql_to_fp.get(sql_key)

    # ---- estimates -------------------------------------------------------
    def estimate_fingerprint(self, fingerprint: str) -> CostEstimate:
        """Mean observed wall over the ring (a robust central tendency
        for repeated queries), max observed peak device bytes (memory
        planning must cover the worst observed case, not the average)."""
        if self._stats is None or not fingerprint:
            return CostEstimate(self.default_ms, self.default_bytes, False)
        try:
            history = self._stats.history(fingerprint)
        except Exception:
            history = []
        if not history:
            return CostEstimate(self.default_ms, self.default_bytes, False)
        walls = []
        peak = 0
        for obs in history:
            try:
                walls.append(float(obs.get("total_ms") or 0.0))
                nbytes = sum(
                    int(t.get("device_bytes") or 0)
                    for t in (obs.get("tasks") or {}).values()
                )
                peak = max(peak, nbytes)
            except Exception:
                continue
        wall = sum(walls) / len(walls) if walls else self.default_ms
        return CostEstimate(
            max(1.0, wall), peak if peak > 0 else self.default_bytes, True
        )

    def estimate_sql(self, sql: str) -> CostEstimate:
        """Submit-time estimate: through the feedback map when this text
        has compiled before, defaults otherwise."""
        fp = self.resolve(sql_cost_key(sql))
        if fp is None:
            return CostEstimate(self.default_ms, self.default_bytes, False)
        return self.estimate_fingerprint(fp)


class PredictiveAdmission:
    """Live planning state: in-flight predicted bytes + backlog cost.

    The scheduler calls :meth:`job_started` / :meth:`job_finished` and
    :meth:`job_queued` / :meth:`job_dequeued`; the daemon reads
    :meth:`predicted_drain_secs` and :meth:`fits_memory`."""

    def __init__(
        self,
        model: QueryCostModel,
        max_concurrent: int = 1,
        memory_fraction: float = 0.8,
        budget_bytes_fn: Any = None,
    ):
        self.model = model
        self._slots = max(1, int(max_concurrent))
        self._memory_fraction = max(0.0, float(memory_fraction))
        # () -> governed device budget bytes (0 = ungoverned)
        self._budget_bytes_fn = budget_bytes_fn or (lambda: 0)
        self._lock = tracked_lock(
            "serve.admission.PredictiveAdmission._lock"
        )
        self._running_bytes = 0
        self._running_ms = 0.0
        self._queued_ms = 0.0
        self._running: Dict[str, CostEstimate] = {}
        self._queued: Dict[str, CostEstimate] = {}

    # ---- scheduler hooks -------------------------------------------------
    def job_queued(self, job_id: str, est: CostEstimate) -> None:
        with self._lock:
            if job_id in self._queued:
                return
            self._queued[job_id] = est
            self._queued_ms += est.wall_ms

    def job_dequeued(self, job_id: str) -> None:
        """The job left the queue WITHOUT starting (cancel, deadline
        expiry, shutdown)."""
        with self._lock:
            est = self._queued.pop(job_id, None)
            if est is not None:
                self._queued_ms = max(0.0, self._queued_ms - est.wall_ms)

    def job_started(self, job_id: str) -> None:
        with self._lock:
            est = self._queued.pop(job_id, None)
            if est is None:
                return
            self._queued_ms = max(0.0, self._queued_ms - est.wall_ms)
            self._running[job_id] = est
            self._running_bytes += est.device_bytes
            self._running_ms += est.wall_ms

    def job_finished(self, job_id: str) -> None:
        with self._lock:
            est = self._running.pop(job_id, None)
            if est is None:
                return
            self._running_bytes = max(
                0, self._running_bytes - est.device_bytes
            )
            self._running_ms = max(0.0, self._running_ms - est.wall_ms)

    # ---- planning reads --------------------------------------------------
    def inflight_bytes(self) -> int:
        with self._lock:
            return self._running_bytes

    def fits_memory(self, est: CostEstimate, anything_running: bool) -> bool:
        """Would starting a job with this prediction keep the in-flight
        predicted bytes inside the planned fraction of the governed
        budget? Ungoverned engines (budget 0) always fit; an idle
        scheduler always admits ONE job regardless (livelock escape — a
        prediction larger than the whole budget must still run, and the
        governor's spill tiers absorb the miss)."""
        if self._memory_fraction <= 0.0:
            return True
        budget = int(self._budget_bytes_fn() or 0)
        if budget <= 0:
            return True
        if not anything_running:
            return True
        with self._lock:
            inflight = self._running_bytes
        return inflight + est.device_bytes <= budget * self._memory_fraction

    def predicted_drain_secs(self) -> float:
        """Predicted seconds until the current backlog (queued + the
        remainder of running, assumed half-done on average) drains
        through the worker slots."""
        with self._lock:
            total_ms = self._queued_ms + self._running_ms / 2.0
        return (total_ms / 1000.0) / self._slots

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "running_jobs": len(self._running),
                "queued_jobs": len(self._queued),
                "inflight_predicted_bytes": self._running_bytes,
                "queued_predicted_ms": round(self._queued_ms, 3),
                "predicted_drain_secs": round(
                    (self._queued_ms + self._running_ms / 2.0)
                    / 1000.0
                    / self._slots,
                    4,
                ),
            }


def make_admission(
    stats_store: Any,
    max_concurrent: int,
    memory_fraction: float,
    default_ms: float,
    default_bytes: int,
    budget_bytes_fn: Any = None,
) -> PredictiveAdmission:
    """The daemon's constructor hook (kept tiny so the self-test's
    admission leg and the daemon build identical objects)."""
    return PredictiveAdmission(
        QueryCostModel(
            stats_store, default_ms=default_ms, default_bytes=default_bytes
        ),
        max_concurrent=max_concurrent,
        memory_fraction=memory_fraction,
        budget_bytes_fn=budget_bytes_fn,
    )
