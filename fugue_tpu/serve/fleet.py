"""Serving fleet (ISSUE 13): a front-tier router spreading sessions
across N daemon replicas, with journal-based failover and rolling
restart — the horizontal-scale composition of the already-hardened
single-daemon pieces (ROADMAP open item 3, the Spark/Ray-Serve fleet
role of PAPER.md §2.7/§2.10-2.11).

Topology::

    clients ──► FleetRouter (HTTP, HardenedRequestHandler stack)
                   │ session affinity: sid → replica, journaled to
                   │ <fugue.serve.state_path>/router_state.json
                   ├──► ServeDaemon replica r0   state: <state>/replicas/r0
                   └──► ServeDaemon replica r1   state: <state>/replicas/r1
    shared fs:  <state>/replicas/<rid>/  (journals + table artifacts)
                <state>/results/         (cross-replica result cache)
                fugue.optimize.cache.dir (shared compiled executables)

**Affinity & routing.** ``POST /v1/sessions`` lands on the healthy
replica with the fewest affined sessions (round-robin tiebreak); every
session- and job-scoped request then follows the affinity map. The map
is journaled through the same atomic-snapshot machinery as the daemon
journal (:class:`~fugue_tpu.serve.state.SnapshotWriter`), so a restarted
router resumes routing existing sessions without guessing.

**Health-driven replica state.** A background poller walks each
replica's ``/v1/health``: ``healthy`` / ``warming`` (prewarm in
progress) / ``draining`` / ``dead``. Transport failures — from the
poller OR from per-request forwards (fault site ``serve.route``) —
count against ``fugue.serve.fleet.death_threshold``; crossing it marks
the replica dead and queues failover.

**Journal-based migration.** Failover (replica death) and planned
drain (rolling restart) are the SAME move: a surviving replica adopts
the lost replica's journal via ``POST /v1/admin/adopt``
(:meth:`~fugue_tpu.serve.daemon.ServeDaemon.adopt_state`) — sessions
rehydrate under their original ids, hot tables reload lazily from the
fingerprint-verified shared-fs artifacts, interrupted async jobs
resubmit under their original job ids, and the source journal is
emptied so the origin replica cannot double-own them. The router then
re-points the affinity map. During the handoff window requests for the
moving sessions answer 503 + ``Retry-After``; the
:class:`~fugue_tpu.serve.client.ServeClient` retry/failover budget
absorbs them, which is what makes a rolling restart under live load
complete with zero failed client calls.

**Observability.** ``GET /v1/metrics`` on the router emits the
router's own families plus every live replica's exposition with a
``replica="<rid>"`` label injected; ``GET /v1/status`` aggregates the
fleet view (states, affinity counts, failovers) over the per-replica
status payloads.

:class:`ServeFleet` is the in-process composition used by tests and the
bench: it owns the N replica daemons + the router, derives the
per-replica state subdirectories from the shared
``fugue.serve.state_path``, and drives
:meth:`~ServeFleet.rolling_restart` (drain → migrate → fresh daemon →
wait healthy, one replica at a time).
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from fugue_tpu.constants import (
    FUGUE_CONF_JAX_DEVICES,
    FUGUE_CONF_OPTIMIZE_CACHE_DIR,
    FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS,
    FUGUE_CONF_SERVE_FLEET_DEATH_THRESHOLD,
    FUGUE_CONF_SERVE_FLEET_DEVICE_SLICES,
    FUGUE_CONF_SERVE_FLEET_HEALTH_INTERVAL,
    FUGUE_CONF_SERVE_FLEET_HOST,
    FUGUE_CONF_SERVE_FLEET_PORT,
    FUGUE_CONF_SERVE_FLEET_REPLICAS,
    FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR,
    FUGUE_CONF_SERVE_PORT,
    FUGUE_CONF_SERVE_STATE_PATH,
    typed_conf_get,
)
from fugue_tpu.fs import make_default_registry
from fugue_tpu.obs import MetricsRegistry
from fugue_tpu.rpc.http import structured_error
from fugue_tpu.serve.http import ServeHTTPServer, dumps
from fugue_tpu.serve.state import SnapshotWriter
from fugue_tpu.serve.supervisor import BackpressureError
from fugue_tpu.testing.faults import fault_point
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.utils.params import ParamDict
from fugue_tpu.workflow.manifest import read_json

_ROUTER_STATE_FILE = "router_state.json"
_MAX_TRACKED_JOBS = 4096

HEALTHY = "healthy"
WARMING = "warming"
DRAINING = "draining"
DEAD = "dead"

# one Prometheus sample line: name[{labels}] value [timestamp]
_METRIC_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(.+)$"
)


def relabel_exposition(text: str, replica: str) -> List[str]:
    """Inject ``replica="<rid>"`` into every sample of a Prometheus
    text exposition (comment lines pass through; the caller dedupes
    HELP/TYPE across replicas)."""
    out: List[str] = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            out.append(line)
            continue
        m = _METRIC_LINE_RE.match(line)
        if m is None:  # pragma: no cover - malformed exposition line
            out.append(line)
            continue
        name, _, inner, value = m.groups()
        labels = f'replica="{replica}"'
        if inner:
            labels = f"{labels},{inner}"
        out.append(f"{name}{{{labels}}} {value}")
    return out


class _Replica:
    """The router's view of one daemon replica."""

    def __init__(self, rid: str, host: str, port: int,
                 state_path: str = ""):
        self.rid = rid
        self.host = host
        self.port = int(port)
        # the replica's OWN journal dir on the shared fs — what a
        # survivor adopts when this replica dies or drains away
        self.state_path = state_path
        self.state = WARMING
        self.fails = 0
        self.last_seen = 0.0

    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def describe(self) -> Dict[str, Any]:
        return {
            "replica": self.rid,
            "address": f"{self.host}:{self.port}",
            "state": self.state,
            "consecutive_failures": self.fails,
            "state_path": self.state_path,
        }


class FleetRouter:
    """The fleet's HTTP front tier. Duck-types the daemon contract the
    hardened serve handler expects (``handle_api`` + ``render_metrics``)
    so it runs on the exact same HTTP stack."""

    def __init__(self, conf: Any = None):
        conf = ParamDict(conf)
        self._fs = make_default_registry()
        self._base = str(
            typed_conf_get(conf, FUGUE_CONF_SERVE_STATE_PATH) or ""
        ).strip()
        self._health_interval = max(
            0.02,
            float(typed_conf_get(conf, FUGUE_CONF_SERVE_FLEET_HEALTH_INTERVAL)),
        )
        self._death_threshold = max(
            1, int(typed_conf_get(conf, FUGUE_CONF_SERVE_FLEET_DEATH_THRESHOLD))
        )
        # failover serializes ABOVE the routing lock: adoption talks to
        # a replica over HTTP and must never run under _lock
        self._failover_lock = tracked_lock(
            "serve.fleet.FleetRouter._failover_lock", reentrant=True
        )
        self._lock = tracked_lock(
            "serve.fleet.FleetRouter._lock", reentrant=True
        )
        self._replicas: Dict[str, _Replica] = {}
        self._affinity: Dict[str, str] = {}   # session id -> replica id
        self._jobs: Dict[str, str] = {}       # job id -> session id
        self._pending_failover: List[str] = []
        self._rr = 0
        self._dirty = False
        self._writer: Optional[SnapshotWriter] = None
        if self._base:
            self._fs.makedirs(self._base, exist_ok=True)
            self._writer = SnapshotWriter(self._fs, self.state_uri)
        http_conf = ParamDict(conf)
        http_conf["fugue.rpc.http_server.host"] = typed_conf_get(
            conf, FUGUE_CONF_SERVE_FLEET_HOST
        )
        http_conf["fugue.rpc.http_server.port"] = typed_conf_get(
            conf, FUGUE_CONF_SERVE_FLEET_PORT
        )
        self._http = ServeHTTPServer(self, http_conf)
        self._started = False
        self._stop_event = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self._metrics = MetricsRegistry()
        self._m_requests = self._metrics.counter(
            "fugue_fleet_requests_total",
            "router HTTP requests by route family and status",
            ["route", "status"],
        )
        self._m_forward_fail = self._metrics.counter(
            "fugue_fleet_forward_failures_total",
            "transport failures forwarding to a replica",
            ["replica"],
        )
        self._m_failover = self._metrics.counter(
            "fugue_fleet_failovers_total",
            "journal adoptions moving sessions off a replica, by mode",
            ["mode"],
        )
        for mode in ("death", "planned"):
            self._m_failover.labels(mode=mode)
        self._m_fenced = self._metrics.counter(
            "fugue_fleet_adoptions_fenced_total",
            "adoption attempts that lost the journal's CAS fence race "
            "to another adopter and backed off",
        )
        self._metrics.add_collector(self._collect_gauges)

    # ---- lifecycle -------------------------------------------------------
    @property
    def state_uri(self) -> str:
        return self._fs.join(self._base, _ROUTER_STATE_FILE)

    @property
    def address(self) -> Tuple[str, int]:
        return self._http.address

    def attach(
        self, rid: str, host: str, port: int, state_path: str = ""
    ) -> None:
        """Register (or re-register after a restart: fresh address,
        reset failure count, back to warming) one replica."""
        with self._lock:
            self._replicas[rid] = _Replica(rid, host, port, state_path)
            if rid in self._pending_failover:
                self._pending_failover.remove(rid)

    def detach(self, rid: str) -> None:
        """Forget one replica entirely (scale-down). The caller is
        responsible for having migrated its sessions first (failover /
        adoption); any affinity entries still pointing at ``rid`` are
        dropped so requests 404 instead of routing at a gone replica."""
        with self._lock:
            self._replicas.pop(rid, None)
            if rid in self._pending_failover:
                self._pending_failover.remove(rid)
            stranded = [
                sid for sid, r in self._affinity.items() if r == rid
            ]
            for sid in stranded:
                self._affinity.pop(sid, None)
            self._dirty = True
        self._journal()

    def start(self) -> "FleetRouter":
        if self._started:
            return self
        if self._writer is not None:
            data = read_json(self._fs, self.state_uri) or {}
            with self._lock:
                self._affinity = dict(data.get("affinity") or {})
                self._jobs = dict(data.get("jobs") or {})
        self.check_health()
        self._stop_event.clear()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="fugue-fleet-health"
        )
        self._health_thread.start()
        self._http.start()
        self._started = True
        return self

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        self._stop_event.set()
        health_thread, self._health_thread = self._health_thread, None
        if health_thread is not None:
            health_thread.join(timeout=5.0)
        self._http.stop()
        self._journal()

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *args: Any) -> None:
        self.stop()

    # ---- affinity journal ------------------------------------------------
    def _journal(self) -> None:
        """Persist the affinity + job maps (snapshot under the routing
        lock, ordered write outside it — same discipline as the daemon
        journal). No-op without a state path: the router still works,
        it just cannot resume its map after ITS OWN restart."""
        if self._writer is None:
            return
        with self._lock:
            payload = {
                "saved_at": time.time(),
                "affinity": dict(self._affinity),
                "jobs": dict(self._jobs),
            }
            self._dirty = False
            ticket = self._writer.ticket()
        self._writer.write(ticket, payload)

    def _maybe_journal(self) -> None:
        if self._writer is None:
            return
        with self._lock:
            if not self._dirty:
                return
        self._journal()

    # ---- replica health --------------------------------------------------
    def replica_state(self, rid: str) -> str:
        with self._lock:
            return self._replicas[rid].state

    def replicas(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.describe() for r in self._replicas.values()]

    def affinity(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._affinity)

    def begin_drain(self, rid: str) -> None:
        """Planned-migration entry (rolling restart): stop routing NEW
        sessions at ``rid`` now; existing-session traffic keeps
        forwarding (the draining daemon itself answers 503 +
        Retry-After for submissions, which the client absorbs)."""
        with self._lock:
            replica = self._replicas.get(rid)
            if replica is not None and replica.state != DEAD:
                replica.state = DRAINING

    def _health_loop(self) -> None:
        while not self._stop_event.wait(self._health_interval):
            try:
                self.check_health()
                self._run_pending_failovers()
                self._maybe_journal()
            except Exception:  # pragma: no cover - poller must survive
                pass

    def check_health(self) -> Dict[str, str]:
        """One synchronous poll pass over every replica (the background
        loop's body; tests and the fleet's restart wait call it directly
        for determinism). Returns {rid: state}."""
        with self._lock:
            replicas = list(self._replicas.values())
        out: Dict[str, str] = {}
        for replica in replicas:
            out[replica.rid] = self._probe(replica)
        return out

    def _probe(self, replica: _Replica) -> str:
        url = f"http://{replica.host}:{replica.port}/v1/health"
        timeout = max(2.0, self._health_interval * 2)
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                body = resp.read()
            state = HEALTHY
        except urllib.error.HTTPError as ex:
            # an HTTP answer (503 warming/draining) is a LIVE replica
            body = ex.read()
            state = DRAINING
        except Exception:
            return self._note_replica_failure(replica.rid)
        try:
            reported = str(json.loads(body.decode("utf-8")).get("state", ""))
            if reported in (HEALTHY, WARMING, DRAINING):
                state = reported
        except Exception:  # pragma: no cover - non-JSON health body
            pass
        with self._lock:
            replica.fails = 0
            replica.last_seen = time.time()
            # a router-side planned drain is sticky until reattach: the
            # daemon still answers "healthy" while the fleet is about to
            # stop it, and new sessions must not land there
            if not (replica.state == DRAINING and state == HEALTHY):
                if replica.state == DEAD:
                    # the corpse answered: transient poll failures, not
                    # a death — CANCEL the queued failover, or the next
                    # tick would adopt a LIVE replica's journal and
                    # double-own its sessions
                    if replica.rid in self._pending_failover:
                        self._pending_failover.remove(replica.rid)
                replica.state = state
        return replica.state

    def _note_replica_failure(self, rid: str) -> str:
        """Count one transport failure against the replica; crossing
        ``fugue.serve.fleet.death_threshold`` marks it dead and queues
        its sessions for adoption by a survivor."""
        self._m_forward_fail.labels(replica=rid).inc()
        with self._lock:
            replica = self._replicas.get(rid)
            if replica is None:  # pragma: no cover - detached mid-flight
                return DEAD
            replica.fails += 1
            if replica.fails < self._death_threshold or replica.state == DEAD:
                return replica.state
            replica.state = DEAD
            if rid not in self._pending_failover:
                self._pending_failover.append(rid)
        return DEAD

    def _run_pending_failovers(self) -> None:
        with self._lock:
            pending = list(self._pending_failover)
        for rid in pending:
            self.failover(rid)

    # ---- failover / migration --------------------------------------------
    def _pick_replica(
        self, exclude: Tuple[str, ...] = ()
    ) -> Optional[str]:
        """The healthy replica owning the fewest sessions (round-robin
        tiebreak); warming replicas only when no healthy one exists
        (they accept submissions, just not compile-free yet)."""
        with self._lock:
            counts: Dict[str, int] = {
                rid: 0 for rid in self._replicas if rid not in exclude
            }
            for sid, rid in self._affinity.items():
                if rid in counts:
                    counts[rid] += 1
            for accept in ((HEALTHY,), (HEALTHY, WARMING)):
                ranked = sorted(
                    (
                        (counts[rid], i, rid)
                        for i, rid in enumerate(self._replicas)
                        if rid not in exclude
                        and self._replicas[rid].state in accept
                    ),
                )
                if ranked:
                    self._rr += 1
                    best = [r for r in ranked if r[0] == ranked[0][0]]
                    return best[self._rr % len(best)][2]
            return None

    def failover(self, rid: str, mode: Optional[str] = None) -> Optional[List[str]]:
        """Move ``rid``'s sessions to a survivor by journal adoption.
        Returns the adopted session ids once the adoption RAN ([] when
        the journal held nothing unexpired), or None when it could not
        run yet (no survivor, adopt call failed, or a death-queued
        replica turned out to be alive) — a death-triggered failover
        stays queued and retries on the next health tick."""
        with self._failover_lock:
            with self._lock:
                replica = self._replicas.get(rid)
                state_path = replica.state_path if replica is not None else ""
                sids = [
                    s for s, r in self._affinity.items() if r == rid
                ]
                mode = mode or (
                    "planned"
                    if replica is not None and replica.state == DRAINING
                    else "death"
                )
                if (
                    mode == "death"
                    and replica is not None
                    and replica.state not in (DEAD, DRAINING)
                ):
                    # revived between queueing and now: adopting a LIVE
                    # replica's journal would double-own its sessions
                    if rid in self._pending_failover:
                        self._pending_failover.remove(rid)
                    return None
            if not state_path:
                # nothing adoptable (ephemeral replica): drop the map
                # entries so requests 404 instead of routing at a corpse
                with self._lock:
                    for sid in sids:
                        self._affinity.pop(sid, None)
                    if rid in self._pending_failover:
                        self._pending_failover.remove(rid)
                    self._dirty = True
                return []
            survivor = self._pick_replica(exclude=(rid,))
            if survivor is None:
                return None  # stays pending; retried on the next tick
            # bounded: this runs on the health-loop thread under the
            # failover lock — a hung adoption must not freeze death
            # detection fleet-wide for the forward default's 600s
            status, body, _ = self._forward(
                survivor, "POST", "/v1/admin/adopt",
                {"state_path": state_path}, timeout=60.0,
            )
            if status != 200:
                err = body.get("error") or {}
                if "AdoptionFenced" in str(err.get("error", "")):
                    # another adopter holds this journal's fence — the
                    # race is settled. Stay pending: once the winner
                    # clears the journal (fence falls with it), the
                    # retry adopts an empty state and settles the map.
                    self._m_fenced.labels().inc()
                return None  # stays pending; retried on the next tick
            adopted = list((body.get("adopted") or {}).get("sessions") or [])
            with self._lock:
                for sid in adopted:
                    self._affinity[sid] = survivor
                for sid in sids:
                    if sid not in adopted:
                        self._affinity.pop(sid, None)  # expired while moving
                if rid in self._pending_failover:
                    self._pending_failover.remove(rid)
            self._m_failover.labels(mode=mode).inc()
            self._journal()
            return adopted

    # ---- forwarding ------------------------------------------------------
    def _forward(
        self,
        rid: str,
        method: str,
        path: str,
        payload: Optional[Dict[str, Any]] = None,
        request_id: Optional[str] = None,
        timeout: float = 600.0,
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        """Forward one request to a replica; transport failures count
        toward its death threshold and answer 503 + Retry-After (the
        client's retry budget bridges the failover window)."""
        fault_point("serve.route", f"{rid} {method} {path}")
        with self._lock:
            replica = self._replicas.get(rid)
            if replica is None or replica.state == DEAD:
                return self._unavailable(rid)
            host, port = replica.address
        headers = {"Content-Type": "application/json"}
        if request_id is not None:
            headers["X-Request-Id"] = request_id
        req = urllib.request.Request(
            f"http://{host}:{port}" + path,
            data=dumps(payload) if payload is not None else None,
            method=method,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                body = json.loads(resp.read().decode("utf-8"))
                out_headers = {
                    k: v
                    for k, v in resp.headers.items()
                    if k.lower() == "retry-after"
                }
                return resp.status, body, out_headers
        except urllib.error.HTTPError as ex:
            try:
                body = json.loads(ex.read().decode("utf-8"))
            except Exception:
                body = {"error": {"error": "HTTPError", "message": str(ex)}}
            out_headers = {
                k: v
                for k, v in (ex.headers or {}).items()
                if k.lower() == "retry-after"
            }
            return ex.code, body, out_headers
        except Exception:
            self._note_replica_failure(rid)
            return self._unavailable(rid)

    def _unavailable(
        self, rid: str
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        err = BackpressureError(
            f"replica {rid} is unavailable; its sessions are being "
            "failed over — retry shortly",
            retry_after=1.0,
        )
        return (
            503,
            {"error": structured_error(err), "retry_after": 1.0},
            {"Retry-After": "1"},
        )

    # ---- bookkeeping on forwarded answers --------------------------------
    def _note_session(self, sid: str, rid: str) -> None:
        with self._lock:
            self._affinity[sid] = rid
        self._journal()

    def _drop_session(self, sid: str) -> None:
        with self._lock:
            self._affinity.pop(sid, None)
        self._journal()

    def _note_job(self, jid: str, sid: str, durable: bool) -> None:
        """Track job → session so /v1/jobs routes through the affinity
        map (and keeps routing correctly AFTER a migration moves the
        session). Async submissions journal immediately — a restarted
        router must resolve a poller's job id; sync ones ride along
        with the next write."""
        with self._lock:
            self._jobs[jid] = sid
            while len(self._jobs) > _MAX_TRACKED_JOBS:
                self._jobs.pop(next(iter(self._jobs)))
            self._dirty = True
        if durable:
            self._journal()

    # ---- the daemon-contract surface (HTTP handler calls these) ----------
    def render_metrics(self) -> str:
        """Router families + every live replica's exposition with a
        ``replica`` label injected; HELP/TYPE comments dedupe across
        replicas (first writer wins)."""
        lines: List[str] = []
        seen_comments: set = set()
        for line in self._metrics.render().splitlines():
            lines.append(line)
            if line.startswith("#"):
                seen_comments.add(line)
        with self._lock:
            replicas = [
                (r.rid, r.address) for r in self._replicas.values()
                if r.state != DEAD
            ]
        for rid, (host, port) in replicas:
            try:
                with urllib.request.urlopen(
                    f"http://{host}:{port}/v1/metrics", timeout=5.0
                ) as resp:
                    text = resp.read().decode("utf-8")
            except Exception:
                continue  # scrape-time: a missing replica just drops out
            for line in relabel_exposition(text, rid):
                if line.startswith("#"):
                    if line in seen_comments:
                        continue
                    seen_comments.add(line)
                lines.append(line)
        return "\n".join(lines) + "\n"

    def _collect_gauges(self) -> None:
        g = self._metrics.gauge(
            "fugue_fleet_replicas", "replicas per router health state",
            ["state"],
        )
        with self._lock:
            states = [r.state for r in self._replicas.values()]
            sessions = len(self._affinity)
        for state in (HEALTHY, WARMING, DRAINING, DEAD):
            g.labels(state=state).set(states.count(state))
        self._metrics.gauge(
            "fugue_fleet_sessions", "sessions tracked in the affinity map"
        ).labels().set(sessions)

    def handle_api(
        self,
        method: str,
        path: str,
        payload: Dict[str, Any],
        request_id: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        """Route one front-tier request (same contract as the daemon's
        ``handle_api``: never raises, structured errors, X-Request-Id on
        every response)."""
        from fugue_tpu.serve.daemon import clean_request_id, new_request_id

        req_id = clean_request_id(request_id) or new_request_id()
        try:
            status, resp, headers = self._handle(
                method, path, payload, req_id
            )
        except KeyError as ex:
            status, resp, headers = 404, {"error": structured_error(ex)}, {}
        except (ValueError, TypeError) as ex:
            status, resp, headers = 400, {"error": structured_error(ex)}, {}
        except Exception as ex:  # defensive: the router must answer
            status, resp, headers = 500, {"error": structured_error(ex)}, {}
        route = path.split("?", 1)[0].split("/")
        family = route[2] if len(route) > 2 and route[1] == "v1" else "unknown"
        self._m_requests.labels(route=family, status=str(status)).inc()
        out_headers = dict(headers)
        out_headers["X-Request-Id"] = req_id
        return status, resp, out_headers

    def _handle(
        self,
        method: str,
        path: str,
        payload: Dict[str, Any],
        request_id: str,
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        parts = [p for p in path.split("?", 1)[0].split("/") if p]
        if not parts or parts[0] != "v1":
            raise KeyError(f"unknown path {path}")
        route = parts[1:]
        if route == ["health"] and method == "GET":
            with self._lock:
                states = {
                    rid: r.state for rid, r in self._replicas.items()
                }
            ok = any(s == HEALTHY for s in states.values())
            return (
                (200 if ok else 503),
                {"ok": ok, "state": HEALTHY if ok else "degraded",
                 "replicas": states},
                {},
            )
        if route == ["status"] and method == "GET":
            return 200, self.status(), {}
        if route == ["fleet"] and method == "GET":
            return 200, self.describe(), {}
        if route == ["sessions"] and method == "POST":
            return self._route_create_session(payload, request_id)
        if route == ["sessions"] and method == "GET":
            return 200, {"sessions": self._gather_sessions(request_id)}, {}
        if len(route) >= 2 and route[0] == "sessions":
            sid = route[1]
            with self._lock:
                owner = self._affinity.get(sid)
            if owner is None:
                raise KeyError(f"unknown or expired session {sid}")
            status, body, headers = self._forward(
                owner, method, path, payload if method == "POST" else None,
                request_id=request_id,
            )
            rest = route[2:]
            if status == 200 and (
                (not rest and method == "DELETE")
                or (rest == ["close"] and method == "POST")
            ):
                self._drop_session(sid)
            if rest == ["sql"] and status in (200, 202):
                jid = body.get("job_id")
                if isinstance(jid, str):
                    self._note_job(jid, sid, durable=status == 202)
            return status, body, headers
        if len(route) >= 2 and route[0] == "jobs":
            jid = route[1]
            with self._lock:
                sid = self._jobs.get(jid)
                owner = self._affinity.get(sid) if sid is not None else None
            if owner is None:
                raise KeyError(f"unknown job {jid}")
            return self._forward(
                owner, method, path,
                payload if method == "POST" else None,
                request_id=request_id,
            )
        raise KeyError(f"unknown route {method} {path}")

    def _route_create_session(
        self, payload: Dict[str, Any], request_id: str
    ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        rid = self._pick_replica()
        if rid is None:
            err = BackpressureError(
                "no healthy replica available for a new session",
                retry_after=1.0,
            )
            return (
                503,
                {"error": structured_error(err), "retry_after": 1.0},
                {"Retry-After": "1"},
            )
        status, body, headers = self._forward(
            rid, "POST", "/v1/sessions", payload, request_id=request_id
        )
        if status == 200 and isinstance(body.get("session_id"), str):
            self._note_session(body["session_id"], rid)
            body = dict(body)
            body["replica"] = rid
        return status, body, headers

    def _gather_sessions(self, request_id: str) -> List[Dict[str, Any]]:
        with self._lock:
            live = [
                r.rid for r in self._replicas.values() if r.state != DEAD
            ]
        out: List[Dict[str, Any]] = []
        for rid in live:
            status, body, _ = self._forward(
                rid, "GET", "/v1/sessions", request_id=request_id,
                timeout=10.0,
            )
            if status == 200:
                for rec in body.get("sessions") or []:
                    rec = dict(rec)
                    rec["replica"] = rid
                    out.append(rec)
        return out

    # ---- aggregate views -------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        with self._lock:
            counts: Dict[str, int] = {}
            for rid in self._affinity.values():
                counts[rid] = counts.get(rid, 0) + 1
            return {
                "replicas": [r.describe() for r in self._replicas.values()],
                "sessions": len(self._affinity),
                "sessions_per_replica": counts,
                "tracked_jobs": len(self._jobs),
                "pending_failovers": list(self._pending_failover),
                "state_uri": self.state_uri if self._base else "",
            }

    def status(self) -> Dict[str, Any]:
        """Fleet-wide ``/v1/status``: the router's topology block plus
        each live replica's own status payload."""
        out: Dict[str, Any] = {"fleet": self.describe(), "replicas": {}}
        with self._lock:
            live = [
                r.rid for r in self._replicas.values() if r.state != DEAD
            ]
        for rid in live:
            status, body, _ = self._forward(
                rid, "GET", "/v1/status", timeout=30.0
            )
            out["replicas"][rid] = (
                body if status == 200 else {"unreachable": True}
            )
        return out


class ServeFleet:
    """An in-process serving fleet: N :class:`ServeDaemon` replicas —
    each with its own engine and a per-replica journal under the shared
    ``fugue.serve.state_path`` — behind one :class:`FleetRouter`.

    The replicas share the persistent executable cache
    (``fugue.optimize.cache.dir``, when set) and the cross-replica
    result cache (``fugue.serve.fleet.result_cache_dir``, defaulted to
    ``<state_path>/results``), so a migrated session warm-starts on its
    new replica. :meth:`rolling_restart` is the planned-migration chaos
    scenario: drain → adopt → fresh daemon → wait healthy, one replica
    at a time, with live traffic riding the client retry budget."""

    def __init__(
        self,
        conf: Any = None,
        replicas: Optional[int] = None,
        engine: Any = "jax",
    ):
        self._conf = ParamDict(conf)
        n = int(
            replicas
            if replicas is not None
            else typed_conf_get(self._conf, FUGUE_CONF_SERVE_FLEET_REPLICAS)
        )
        if n < 1:
            raise ValueError(
                "a fleet needs replicas >= 1 (set the replicas argument "
                f"or {FUGUE_CONF_SERVE_FLEET_REPLICAS})"
            )
        base = str(
            typed_conf_get(self._conf, FUGUE_CONF_SERVE_STATE_PATH) or ""
        ).strip()
        if base == "":
            raise ValueError(
                "a fleet requires a shared fugue.serve.state_path: the "
                "per-replica journals under it are what failover adopts"
            )
        self._engine_spec = engine
        self._base = base.rstrip("/")
        fs = make_default_registry()
        if FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR in self._conf:
            # explicit conf wins — including an explicit '' = OFF (the
            # bench uses that to measure execution, not cache reads)
            self._result_dir = str(
                self._conf[FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR] or ""
            ).strip()
        else:
            self._result_dir = fs.join(self._base, "results")
        self._replica_ids = [f"r{i}" for i in range(n)]
        device_slices = self._device_slices(n)
        self._sliced = device_slices is not None
        self._replica_confs: Dict[str, ParamDict] = {}
        for i, rid in enumerate(self._replica_ids):
            self._replica_confs[rid] = self._make_replica_conf(
                rid, device_slices[i] if device_slices is not None else None
            )
        self._daemons: Dict[str, Any] = {}
        self._router = FleetRouter(self._conf)
        # serializes replica-set mutation (add/retire/restart) against
        # the autoscaler thread — OUTERMOST in the canonical order: the
        # guarded operations call into the router (failover/attach) and
        # through it into replica HTTP forwards
        self._lock = tracked_lock(
            "serve.fleet.ServeFleet._lock", reentrant=True
        )
        self._autoscaler: Any = None
        if (
            int(
                typed_conf_get(
                    self._conf, FUGUE_CONF_SERVE_AUTOSCALE_MAX_REPLICAS
                )
            )
            > 0
        ):
            from fugue_tpu.serve.autoscale import FleetAutoscaler

            self._autoscaler = FleetAutoscaler(self, self._conf)
        self._started = False

    def _make_replica_conf(
        self, rid: str, device_slice: Optional[str] = None
    ) -> ParamDict:
        """One replica's derived conf: its own journal subdirectory and
        an ephemeral port, the shared result-cache dir, optionally a
        pinned device slice. The ``fugue.serve.autoscale.*`` keys stay
        at the FLEET level — the controller lives on the ServeFleet, and
        an embedded daemon carrying them would trip FWF508's inert-conf
        gate."""
        rconf = ParamDict(self._conf)
        for key in [
            k for k in rconf.keys()
            if k.startswith("fugue.serve.autoscale.")
        ]:
            del rconf[key]
        rconf[FUGUE_CONF_SERVE_STATE_PATH] = self.replica_state_path(rid)
        rconf[FUGUE_CONF_SERVE_FLEET_RESULT_CACHE_DIR] = self._result_dir
        rconf[FUGUE_CONF_SERVE_PORT] = 0  # ephemeral: never collide
        if device_slice is not None:
            rconf[FUGUE_CONF_JAX_DEVICES] = device_slice
        return rconf

    def _device_slices(self, n: int) -> Optional[List[str]]:
        """With ``fugue.serve.fleet.device_slices`` on, carve
        ``jax.devices()`` into ``n`` contiguous per-replica slices (each
        replica's engine then builds its mesh over its own devices via
        ``fugue.jax.devices`` — HBM and collectives fully isolated
        between replicas). Requires at least one device per replica;
        raises otherwise, since silently sharing devices would defeat
        the isolation the knob asks for. Leftover devices (ndev not
        divisible by n) go to the last replica."""
        if not bool(
            self._conf.get(FUGUE_CONF_SERVE_FLEET_DEVICE_SLICES, False)
        ):
            return None
        import jax

        ndev = len(jax.devices())
        if ndev < n:
            raise ValueError(
                f"{FUGUE_CONF_SERVE_FLEET_DEVICE_SLICES}: {n} replicas "
                f"need at least one device each, but only {ndev} "
                "devices are visible"
            )
        per = ndev // n
        out: List[str] = []
        for i in range(n):
            lo = i * per
            hi = (i + 1) * per if i < n - 1 else ndev
            out.append(",".join(str(d) for d in range(lo, hi)))
        return out

    # ---- lifecycle -------------------------------------------------------
    def replica_state_path(self, rid: str) -> str:
        fs = make_default_registry()
        return fs.join(self._base, "replicas", rid)

    @property
    def router(self) -> FleetRouter:
        return self._router

    @property
    def address(self) -> Tuple[str, int]:
        """(host, port) of the ROUTER's HTTP front tier."""
        return self._router.address

    @property
    def replica_ids(self) -> List[str]:
        with self._lock:
            return list(self._replica_ids)

    @property
    def autoscaler(self) -> Any:
        """The fleet's :class:`~fugue_tpu.serve.autoscale.FleetAutoscaler`
        when ``fugue.serve.autoscale.max_replicas`` > 0, else None."""
        return self._autoscaler

    def replica(self, rid: str) -> Any:
        return self._daemons[rid]

    def shares_exec_cache(self) -> bool:
        return (
            str(
                typed_conf_get(self._conf, FUGUE_CONF_OPTIMIZE_CACHE_DIR)
                or ""
            ).strip()
            != ""
        )

    def start(self) -> "ServeFleet":
        if self._started:
            return self
        from fugue_tpu.serve.daemon import ServeDaemon

        for rid in self._replica_ids:
            daemon = ServeDaemon(
                self._replica_confs[rid], self._engine_spec
            ).start()
            self._daemons[rid] = daemon
            host, port = daemon.address
            self._router.attach(
                rid, host, port, state_path=self.replica_state_path(rid)
            )
        self._router.start()
        self._started = True
        if self._autoscaler is not None:
            self._autoscaler.start()
        return self

    def stop(self, drain: bool = False) -> None:
        if not self._started:
            return
        self._started = False
        if self._autoscaler is not None:
            self._autoscaler.stop()
        self._router.stop()
        for daemon in self._daemons.values():
            try:
                daemon.stop(drain=drain)
            except Exception:  # pragma: no cover - best-effort teardown
                pass

    def __enter__(self) -> "ServeFleet":
        return self.start()

    def __exit__(self, *args: Any) -> None:
        self.stop()

    # ---- chaos / rolling restart -----------------------------------------
    def kill_replica(self, rid: str) -> None:
        """Chaos hook: the in-process stand-in for ``kill -9`` on one
        replica (no drain, no final journal write). The router's health
        loop detects the corpse and fails its sessions over."""
        self._daemons[rid]._hard_kill()

    def restart_replica(
        self, rid: str, timeout: float = 120.0
    ) -> Dict[str, Any]:
        """One rolling-restart step: planned migration then a fresh
        daemon. Drain the replica (its final journal snapshot lands
        BEFORE the engine closes), adopt its journal into a survivor,
        start a fresh daemon on the same slot, and wait until the
        router sees it healthy again."""
        with self._lock:
            t0 = time.monotonic()
            self._router.begin_drain(rid)
            self._daemons[rid].stop(drain=True)
            migrated = self._router.failover(rid, mode="planned")
            t_migrated = time.monotonic()
            if migrated is not None:
                self._ensure_origin_journal_clear(rid)
            from fugue_tpu.serve.daemon import ServeDaemon

            fresh = ServeDaemon(
                self._replica_confs[rid], self._engine_spec
            ).start()
            self._daemons[rid] = fresh
            host, port = fresh.address
            self._router.attach(
                rid, host, port, state_path=self.replica_state_path(rid)
            )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._router.check_health().get(rid) == HEALTHY:
                break
            time.sleep(0.05)
        else:  # pragma: no cover - replica failed to come back
            raise TimeoutError(
                f"replica {rid} did not report healthy within {timeout}s "
                "after its rolling restart"
            )
        return {
            "replica": rid,
            "migrated_sessions": len(migrated or []),
            # None = no survivor was available: the fresh daemon
            # recovered its own journal instead (single-daemon path)
            "migration_ran": migrated is not None,
            "migration_secs": round(t_migrated - t0, 4),
            "secs": round(time.monotonic() - t0, 4),
        }

    def _ensure_origin_journal_clear(self, rid: str) -> None:
        """After an adoption RAN, the origin journal MUST be empty
        before the slot is reused (fresh daemon) or forgotten (retire)
        — adopt_state clears it, but a shared-fs hiccup there only logs
        on the survivor. Verify here and refuse to double-own: a daemon
        rehydrating just-migrated sessions would later delete the
        shared artifacts the survivor depends on."""
        from fugue_tpu.serve.state import ServeStateJournal

        fs = make_default_registry()
        state_path = self.replica_state_path(rid)
        leftover = ServeStateJournal.read_state(fs, state_path)
        if leftover["sessions"] or leftover["jobs"]:
            ServeStateJournal.clear_state(fs, state_path)

    # ---- elastic scale (ISSUE 18) ----------------------------------------
    def add_replica(self, timeout: float = 120.0) -> str:
        """Scale up by one replica: mint the next free ``r<i>`` slot,
        start a fresh daemon on it, attach it to the router, and wait
        until it reports healthy. Returns the new replica id.

        Refused under ``fugue.serve.fleet.device_slices``: the static
        device carve-up is computed for the boot-time replica count and
        cannot be re-partitioned under live engines."""
        if self._sliced:
            raise ValueError(
                f"{FUGUE_CONF_SERVE_FLEET_DEVICE_SLICES}: cannot scale "
                "out a device-sliced fleet — the per-replica slices are "
                "fixed at boot"
            )
        with self._lock:
            i = 0
            while f"r{i}" in self._replica_confs:
                i += 1
            rid = f"r{i}"
            fault_point("serve.scale", f"up {rid}")
            rconf = self._make_replica_conf(rid)
            from fugue_tpu.serve.daemon import ServeDaemon

            daemon = ServeDaemon(rconf, self._engine_spec).start()
            self._replica_confs[rid] = rconf
            self._daemons[rid] = daemon
            self._replica_ids.append(rid)
            host, port = daemon.address
            self._router.attach(
                rid, host, port, state_path=self.replica_state_path(rid)
            )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._router.check_health().get(rid) == HEALTHY:
                break
            time.sleep(0.05)
        else:  # pragma: no cover - replica failed to come up
            raise TimeoutError(
                f"replica {rid} did not report healthy within {timeout}s "
                "after scale-up"
            )
        return rid

    def retire_replica(self, rid: str) -> Dict[str, Any]:
        """Scale down by one replica with the SAME provably-loss-free
        move as a rolling restart: drain (final journal snapshot lands
        before the engine closes) → planned journal adoption into a
        survivor → verify the origin journal is empty → detach.

        A hard kill anywhere in this window (chaos site ``serve.scale``)
        cannot lose sessions: the drained journal is already on the
        shared fs, so the router's death failover adopts it instead —
        the planned and unplanned paths converge on the same journal."""
        with self._lock:
            if rid not in self._daemons:
                raise KeyError(f"unknown replica {rid!r}")
            if len(self._replica_ids) <= 1:
                raise ValueError(
                    "cannot retire the last replica: a fleet needs a "
                    "survivor to adopt the retiring journal"
                )
            t0 = time.monotonic()
            self._router.begin_drain(rid)
            self._daemons[rid].stop(drain=True)
            fault_point("serve.scale", f"down {rid}")
            migrated = self._router.failover(rid, mode="planned")
            if migrated is None:
                # no survivor could adopt RIGHT NOW (transient): leave
                # the replica attached — its daemon is stopped, so the
                # health loop's death failover finishes the migration
                # from the same drained journal on a later tick
                raise RuntimeError(
                    f"retiring {rid}: no survivor adopted its journal; "
                    "replica left attached for death failover"
                )
            self._ensure_origin_journal_clear(rid)
            self._router.detach(rid)
            self._daemons.pop(rid, None)
            self._replica_ids.remove(rid)
            self._replica_confs.pop(rid, None)
            return {
                "replica": rid,
                "migrated_sessions": len(migrated),
                "secs": round(time.monotonic() - t0, 4),
            }

    def rolling_restart(self, timeout: float = 120.0) -> Dict[str, Any]:
        """Restart every replica in sequence under live load — the
        fleet's headline chaos scenario. Sessions migrate off each
        replica before it stops and spread back as later restarts
        migrate onto the fresh daemons; client calls ride their retry
        budget through each handoff window."""
        t0 = time.monotonic()
        steps = [
            self.restart_replica(rid, timeout=timeout)
            for rid in self._replica_ids
        ]
        return {
            "replicas": steps,
            "migrated_sessions": sum(s["migrated_sessions"] for s in steps),
            "migration_secs": round(
                sum(s["migration_secs"] for s in steps), 4
            ),
            "secs": round(time.monotonic() - t0, 4),
        }
