"""Serve sessions: the per-client unit of state in the daemon.

A session owns a namespaced slice of the engine's table catalog — every
table it saves lands under ``__serve__.<session_id>.<name>`` via the
engine's ``SQLEngine.save_table`` (the jax SQL engine keeps the
PERSISTED device-resident frame, so a hot table survives across requests
without re-ingest) — and doubles as the memory governor's *tenant*: its
saved tables are claimed with :meth:`MemoryGovernor.assign_tenant`, so
per-tenant budget accounting and fair spill ordering see exactly the
bytes this session pins. Closing the session drops every table from the
catalog; the ledger reconciles to zero through the frames' weakref
finalizers the moment the last reference dies.
"""

import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from fugue_tpu.dataframe import DataFrame
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.workflow.fault import engine_dispatch_guard

_NAMESPACE = "__serve__"


class ServeSession:
    """One client's hot state against the shared persistent engine."""

    def __init__(self, engine: Any, ttl: float = 0.0):
        self.session_id = "s-" + uuid.uuid4().hex[:12]
        self._engine = engine
        self.ttl = max(0.0, float(ttl))
        self.created_at = time.time()
        self._last_used = time.monotonic()
        self._tables: Dict[str, str] = {}  # name -> qualified catalog name
        self._lock = threading.RLock()
        self._closed = False

    # ---- lifecycle -------------------------------------------------------
    def touch(self) -> None:
        self._last_used = time.monotonic()

    @property
    def idle_seconds(self) -> float:
        return time.monotonic() - self._last_used

    @property
    def expired(self) -> bool:
        return self.ttl > 0 and self.idle_seconds > self.ttl

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> List[str]:
        """Drop every session table from the catalog; returns the dropped
        names. Idempotent."""
        with self._lock:
            if self._closed:
                return []
            self._closed = True
            dropped = list(self._tables)
            sql = self._engine.sql_engine
            for name, qualified in self._tables.items():
                try:
                    sql.drop_table(qualified)
                except Exception:  # pragma: no cover - best-effort cleanup
                    pass
            self._tables.clear()
            return dropped

    # ---- table catalog (namespaced) --------------------------------------
    def qualified(self, name: str) -> str:
        return f"{_NAMESPACE}.{self.session_id}.{name}"

    def save_table(self, name: str, df: DataFrame) -> str:
        """Persist ``df`` as a hot session table and claim its bytes for
        this session's tenant account in the memory governor."""
        assert_or_throw(
            name.isidentifier(),
            ValueError(f"invalid table name {name!r}"),
        )
        q = self.qualified(name)
        with self._lock:
            assert_or_throw(
                not self._closed, ValueError("session is closed")
            )
            sql = self._engine.sql_engine
            # persist runs device programs: serialize with concurrent
            # jobs sharing the engine (see task_execution_lock)
            with engine_dispatch_guard(self._engine, None):
                sql.save_table(df, q, mode="overwrite")
            self._claim_tenant(sql.load_table(q))
            self._tables[name] = q
        self.touch()
        return q

    def _claim_tenant(self, loaded: DataFrame) -> None:
        gov = getattr(self._engine, "memory_governor", None)
        blocks = getattr(loaded, "native", None)
        if gov is not None and blocks is not None:
            gov.assign_tenant(blocks, self.session_id)

    def drop_table(self, name: str) -> None:
        with self._lock:
            q = self._tables.pop(name, None)
        if q is not None:
            self._engine.sql_engine.drop_table(q)

    def table_names(self) -> List[str]:
        with self._lock:
            return sorted(self._tables)

    def table_frames(self) -> Dict[str, DataFrame]:
        """The live session tables as engine dataframes — fed into
        FugueSQL compilation as named sources, so a query just says
        ``SELECT ... FROM mytable``."""
        with self._lock:
            items = list(self._tables.items())
        sql = self._engine.sql_engine
        return {name: sql.load_table(q) for name, q in items}

    def describe(self) -> Dict[str, Any]:
        return {
            "session_id": self.session_id,
            "created_at": self.created_at,
            "idle_seconds": round(self.idle_seconds, 3),
            "ttl": self.ttl,
            "tables": self.table_names(),
        }


class SessionManager:
    """Session registry with lazy TTL expiry: every lookup sweeps the
    expired (closing them drops their tables, so an abandoned session
    cannot pin device memory forever)."""

    def __init__(self, engine: Any, default_ttl: float = 0.0):
        self._engine = engine
        self._default_ttl = max(0.0, float(default_ttl))
        self._sessions: Dict[str, ServeSession] = {}
        self._lock = threading.RLock()

    def create(self, ttl: Optional[float] = None) -> ServeSession:
        session = ServeSession(
            self._engine,
            ttl=self._default_ttl if ttl is None else float(ttl),
        )
        with self._lock:
            self._sessions[session.session_id] = session
        self.sweep()
        return session

    def get(self, session_id: str) -> ServeSession:
        """Raises ``KeyError`` for unknown AND expired ids (an expired
        session is closed on discovery)."""
        self.sweep()
        with self._lock:
            session = self._sessions.get(session_id)
        if session is None:
            raise KeyError(f"unknown or expired session {session_id}")
        session.touch()
        return session

    def close(self, session_id: str) -> List[str]:
        with self._lock:
            session = self._sessions.pop(session_id, None)
        if session is None:
            raise KeyError(f"unknown or expired session {session_id}")
        return session.close()

    def close_all(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close()

    def sweep(self) -> int:
        """Close every expired session; returns how many were closed."""
        with self._lock:
            expired = [
                (sid, s) for sid, s in self._sessions.items() if s.expired
            ]
            for sid, _ in expired:
                del self._sessions[sid]
        for _, s in expired:
            s.close()
        return len(expired)

    def count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def describe(self) -> List[Dict[str, Any]]:
        with self._lock:
            sessions = list(self._sessions.values())
        return [s.describe() for s in sessions]
