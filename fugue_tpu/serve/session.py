"""Serve sessions: the per-client unit of state in the daemon.

A session owns a namespaced slice of the engine's table catalog — every
table it saves lands under ``__serve__.<session_id>.<name>`` via the
engine's ``SQLEngine.save_table`` (the jax SQL engine keeps the
PERSISTED device-resident frame, so a hot table survives across requests
without re-ingest) — and doubles as the memory governor's *tenant*: its
saved tables are claimed with :meth:`MemoryGovernor.assign_tenant`, so
per-tenant budget accounting and fair spill ordering see exactly the
bytes this session pins. Closing the session drops every table from the
catalog; the ledger reconciles to zero through the frames' weakref
finalizers the moment the last reference dies.

**Durability** (ISSUE 7): with a :class:`~fugue_tpu.serve.state.ServeStateJournal`
attached, ``save_table`` also writes the frame as a parquet artifact
under the state path and journals its sha256 fingerprint. A session
restored after a daemon restart starts with *durable records* instead of
catalog entries; the first access to a table re-verifies the fingerprint
(:func:`~fugue_tpu.workflow.manifest.artifact_fingerprint`) and lazily
reloads the artifact into the catalog — corrupt artifacts are removed
and the table forgotten (counted in ``integrity_rejected``), the same
rejection manifest resume applies to checkpoints.
"""

import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from fugue_tpu.dataframe import DataFrame
from fugue_tpu.lake.format import format_lake_uri, is_lake_uri, parse_lake_uri
from fugue_tpu.testing.faults import fault_point
from fugue_tpu.testing.locktrace import tracked_lock
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.workflow.fault import engine_dispatch_guard
from fugue_tpu.workflow.manifest import artifact_fingerprint

_NAMESPACE = "__serve__"


class ServeSession:
    """One client's hot state against the shared persistent engine."""

    def __init__(
        self,
        engine: Any,
        ttl: float = 0.0,
        journal: Any = None,
        session_id: Optional[str] = None,
        created_at: Optional[float] = None,
    ):
        self.session_id = session_id or ("s-" + uuid.uuid4().hex[:12])
        self._engine = engine
        self._journal = journal
        self.ttl = max(0.0, float(ttl))
        self.created_at = created_at if created_at is not None else time.time()
        self._last_used = time.monotonic()
        self._tables: Dict[str, str] = {}  # name -> qualified catalog name
        # bumped on every catalog mutation (save/drop): the daemon's
        # cross-request result cache keys on it, so a resubmitted query
        # after a table update can never serve the stale payload
        self.cache_epoch = 0
        # tables known only from the journal after a restart:
        # name -> {"artifact", "size", "sha256"}; loaded lazily
        self._durable: Dict[str, Dict[str, Any]] = {}
        # durable records of CATALOG-live tables (set at save/reload):
        # the artifact URI is authoritative here — an ADOPTED session's
        # artifacts live under the ORIGIN replica's state dir, not where
        # this daemon's journal would derive them — and the sha256s are
        # the content keys of the fleet's cross-replica result cache
        self._artifacts: Dict[str, Dict[str, Any]] = {}
        self.integrity_rejected = 0
        self.restored = False
        self._lock = tracked_lock(
            "serve.session.ServeSession._lock", reentrant=True
        )
        self._closed = False

    @classmethod
    def restore(
        cls,
        engine: Any,
        journal: Any,
        session_id: str,
        record: Dict[str, Any],
    ) -> "ServeSession":
        """Rehydrate a journaled session: same id/ttl/created_at, table
        records kept durable-only until first access reloads them."""
        s = cls(
            engine,
            ttl=float(record.get("ttl", 0.0) or 0.0),
            journal=journal,
            session_id=session_id,
            created_at=record.get("created_at"),
        )
        s._durable = {
            name: dict(rec)
            for name, rec in (record.get("tables") or {}).items()
            if rec.get("artifact")
        }
        s.restored = True
        # the restored session's cache_epoch restarts at 0 while the
        # PROCESS-wide plan cache may still hold this session id's
        # pre-restart payload entries (in-process kill-restart): drop
        # them, or a post-restart save could realign the epoch and
        # serve a stale payload
        try:
            from fugue_tpu.optimize import get_plan_cache

            get_plan_cache().invalidate_tag(session_id)
        except Exception:  # pragma: no cover - best-effort hygiene
            pass
        return s

    # ---- lifecycle -------------------------------------------------------
    def touch(self) -> None:
        self._last_used = time.monotonic()
        if self._journal is not None:
            self._journal.touch_session(self.session_id)

    @property
    def idle_seconds(self) -> float:
        return time.monotonic() - self._last_used

    @property
    def expired(self) -> bool:
        return self.ttl > 0 and self.idle_seconds > self.ttl

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self, forget: bool = True) -> List[str]:
        """Drop every session table from the catalog; returns the dropped
        names. Idempotent. ``forget=True`` (user close / TTL expiry) also
        removes the journal records and durable artifacts; daemon
        shutdown passes ``forget=False`` so the journaled state survives
        for the next daemon to rehydrate."""
        with self._lock:
            if self._closed:
                return []
            self._closed = True
            dropped = sorted(set(self._tables) | set(self._durable))
            sql = self._engine.sql_engine
            for name, qualified in self._tables.items():
                try:
                    sql.drop_table(qualified)
                except Exception:  # pragma: no cover - best-effort cleanup
                    pass
            if forget and self._journal is not None:
                for name in dropped:
                    self._remove_artifact(name)
                self._journal.forget_session(self.session_id)
            self._tables.clear()
            self._durable.clear()
            self._artifacts.clear()
            # a closing session's cached query payloads die with it
            try:
                from fugue_tpu.optimize import get_plan_cache

                get_plan_cache().invalidate_tag(self.session_id)
            except Exception:  # pragma: no cover - best-effort cleanup
                pass
            return dropped

    def _remove_artifact(self, name: str) -> None:
        if self._journal is None:
            return
        rec = self._artifacts.get(name) or self._durable.get(name) or {}
        uri = rec.get("artifact") or self._journal.table_artifact_uri(
            self.session_id, name
        )
        if is_lake_uri(uri):
            # lake-backed tables are SHARED versioned tables: a session
            # closing forgets its pinned-snapshot record, never the data
            # (other replicas/pipelines may hold other versions live)
            return
        try:
            if self._engine.fs.exists(uri):
                self._engine.fs.rm(uri, recursive=True)
        except Exception:  # pragma: no cover - best-effort cleanup
            pass

    # ---- table catalog (namespaced) --------------------------------------
    def qualified(self, name: str) -> str:
        return f"{_NAMESPACE}.{self.session_id}.{name}"

    def save_table(self, name: str, df: DataFrame) -> str:
        """Persist ``df`` as a hot session table, claim its bytes for
        this session's tenant account in the memory governor, and (with
        a journal) write the durable parquet artifact + fingerprint."""
        assert_or_throw(
            name.isidentifier(),
            ValueError(f"invalid table name {name!r}"),
        )
        q = self.qualified(name)
        with self._lock:
            assert_or_throw(
                not self._closed, ValueError("session is closed")
            )
            sql = self._engine.sql_engine
            # persist runs device programs: serialize with concurrent
            # jobs sharing the engine (see task_execution_lock)
            with engine_dispatch_guard(self._engine, None):
                sql.save_table(df, q, mode="overwrite")
            loaded = sql.load_table(q)
            self._claim_tenant(loaded)
            self._tables[name] = q
            # catalog copy is now the truth; an overwritten durable-only
            # record (adopted, never queried) becomes the PRIOR artifact
            # so _journal_table can clean the origin replica's file up
            durable_prior = self._durable.pop(name, None)
            if (
                name not in self._artifacts
                and durable_prior
                and durable_prior.get("artifact")
            ):
                self._artifacts[name] = dict(durable_prior)
            self.cache_epoch += 1
            self._journal_table(name, loaded)
        self.touch()
        return q

    def _journal_table(self, name: str, df: DataFrame) -> None:
        """Write the durable artifact + fingerprint record (no-op for an
        ephemeral daemon). Artifact write failures degrade durability,
        never the request — the catalog save already succeeded."""
        if self._journal is None:
            return
        lake_base = self._lake_serve_base()
        if lake_base:
            self._journal_table_lake(name, df, lake_base)
            return
        uri = self._journal.table_artifact_uri(self.session_id, name)
        prior = (self._artifacts.get(name) or {}).get("artifact")
        try:
            with engine_dispatch_guard(self._engine, None):
                self._engine.save_df(df, uri, format_hint="parquet")
            size, sha256 = artifact_fingerprint(self._engine.fs, uri)
        except Exception as ex:
            self._engine.log.warning(
                "fugue_tpu serve: durable artifact for table %s.%s failed "
                "(%s: %s); table is hot but will not survive a restart",
                self.session_id, name, type(ex).__name__, ex,
            )
            return
        rec = {"artifact": uri, "size": size, "sha256": sha256}
        self._artifacts[name] = dict(rec)
        self._journal.record_table(self.session_id, name, rec)
        if prior and prior != uri:
            # an ADOPTED session's prior artifact lives under the ORIGIN
            # replica's state dir: the re-save above wrote this journal's
            # own path, so the origin file would leak on the shared fs
            # forever once the record stops pointing at it
            try:
                if self._engine.fs.exists(prior):
                    self._engine.fs.rm(prior, recursive=True)
            except Exception:  # pragma: no cover - best-effort cleanup
                pass

    def _lake_serve_base(self) -> str:
        """``fugue.lake.serve.path``: when set, durable session tables
        commit to SHARED versioned lake tables under this base instead
        of per-session parquet artifacts — a materialized view saved on
        one replica becomes a snapshot any replica (or any offline
        reader) loads by pinned version."""
        from fugue_tpu.constants import (
            FUGUE_CONF_LAKE_SERVE_PATH,
            typed_conf_get,
        )

        try:
            conf = getattr(self._engine, "conf", None) or {}
            return str(typed_conf_get(conf, FUGUE_CONF_LAKE_SERVE_PATH) or "")
        except Exception:  # pragma: no cover - conf shape surprises
            return ""

    def _journal_table_lake(
        self, name: str, df: DataFrame, lake_base: str
    ) -> None:
        """Lake-backed durability: overwrite-commit the frame into
        ``<base>/<name>`` and journal a record pinned to the COMMITTED
        VERSION — ``{"artifact": "lake://...?version=V", "sha256":
        <manifest sha>}``. The sha doubles as the fleet result cache's
        content key, and the pin means a restart reloads exactly what
        was saved even if the shared table has moved on since."""
        from fugue_tpu.lake import LakeTable

        table_uri = self._engine.fs.join(lake_base, name)
        try:
            with engine_dispatch_guard(self._engine, None):
                local = df.as_local_bounded().as_arrow(type_safe=True)
            lt = LakeTable(
                table_uri, fs=self._engine.fs,
                conf=getattr(self._engine, "conf", None) or {},
            )
            manifest = lt.overwrite(local)
        except Exception as ex:
            self._engine.log.warning(
                "fugue_tpu serve: lake commit for table %s.%s failed "
                "(%s: %s); table is hot but will not survive a restart",
                self.session_id, name, type(ex).__name__, ex,
            )
            return
        rec = {
            "artifact": format_lake_uri(table_uri, manifest.version),
            "size": sum(f.nbytes for f in manifest.files),
            "sha256": manifest.sha256,
        }
        self._artifacts[name] = dict(rec)
        self._journal.record_table(self.session_id, name, rec)

    def _claim_tenant(self, loaded: DataFrame) -> None:
        gov = getattr(self._engine, "memory_governor", None)
        blocks = getattr(loaded, "native", None)
        if gov is not None and blocks is not None:
            gov.assign_tenant(blocks, self.session_id)

    def _ensure_loaded(self, name: str) -> Optional[str]:
        """Resolve a durable-only table into the catalog (lazy restart
        reload). Caller holds the lock. Returns the qualified name, or
        None when the record was integrity-rejected and dropped."""
        if name in self._tables:
            return self._tables[name]
        rec = self._durable.get(name)
        if rec is None:
            return None
        uri = rec["artifact"]
        fs = self._engine.fs
        if is_lake_uri(uri):
            # pinned lake snapshot: the integrity check is the MANIFEST
            # sha (manifests are write-once, so a matching sha proves the
            # whole snapshot: every data file is content-addressed by it)
            try:
                from fugue_tpu.lake import LakeTable

                table_uri, pin = parse_lake_uri(uri)
                m = LakeTable(table_uri, fs=fs).read_manifest(
                    int(pin["version"])
                )
                ok = not rec.get("sha256") or m.sha256 == rec["sha256"]
            except Exception:
                ok = False
            if not ok:
                # forget the record but NEVER remove shared lake data
                self.integrity_rejected += 1
                self._engine.log.warning(
                    "fugue_tpu serve: table %s.%s lake snapshot %s failed "
                    "the integrity check on restart reload; dropping the "
                    "record",
                    self.session_id, name, uri,
                )
                self._durable.pop(name, None)
                if self._journal is not None:
                    self._journal.forget_table(self.session_id, name)
                return None
        else:
            try:
                ok = fs.exists(uri)
                if ok and rec.get("sha256"):
                    size, digest = artifact_fingerprint(fs, uri)
                    ok = digest == rec["sha256"] and (
                        rec.get("size") is None or size == rec["size"]
                    )
            except Exception:
                ok = False
        if not ok:
            # same policy as manifest resume: a corrupt artifact is
            # removed and never served — the table is forgotten rather
            # than silently yielding garbage rows
            self.integrity_rejected += 1
            self._engine.log.warning(
                "fugue_tpu serve: table %s.%s artifact %s failed the "
                "integrity check on restart reload; dropping the record",
                self.session_id, name, uri,
            )
            self._durable.pop(name, None)
            try:
                if fs.exists(uri):
                    fs.rm(uri, recursive=True)
            except Exception:  # pragma: no cover - best effort
                pass
            if self._journal is not None:
                self._journal.forget_table(self.session_id, name)
            return None
        q = self.qualified(name)
        sql = self._engine.sql_engine
        with engine_dispatch_guard(self._engine, None):
            df = self._engine.load_df(uri, format_hint="parquet")
            sql.save_table(df, q, mode="overwrite")
        self._claim_tenant(sql.load_table(q))
        self._tables[name] = q
        self._artifacts[name] = dict(rec)
        self._durable.pop(name, None)
        return q

    def drop_table(self, name: str) -> None:
        with self._lock:
            q = self._tables.pop(name, None)
            self.cache_epoch += 1
            self._remove_artifact(name)
            self._durable.pop(name, None)
            self._artifacts.pop(name, None)
        if self._journal is not None:
            self._journal.forget_table(self.session_id, name)
        if q is not None:
            self._engine.sql_engine.drop_table(q)

    def table_names(self) -> List[str]:
        with self._lock:
            return sorted(set(self._tables) | set(self._durable))

    def table_content_keys(self) -> Optional[List[List[str]]]:
        """Sorted ``[name, sha256]`` pairs over every session table — the
        content-addressed part of the fleet's cross-replica result-cache
        key (same artifacts => same key on ANY replica, and the sha
        changes the moment a save changes the table). None when any
        table has no verified durable record (artifact write failed, or
        an ephemeral daemon): a content-keyed cache must not guess."""
        with self._lock:
            names = set(self._tables) | set(self._durable)
            out: List[List[str]] = []
            for name in sorted(names):
                rec = self._artifacts.get(name) or self._durable.get(name)
                sha = (rec or {}).get("sha256")
                if not sha:
                    return None
                out.append([name, str(sha)])
            return out

    def table_frames(self) -> Dict[str, DataFrame]:
        """The live session tables as engine dataframes — fed into
        FugueSQL compilation as named sources, so a query just says
        ``SELECT ... FROM mytable``. Durable-only records (restart)
        reload lazily here, on the session's first query."""
        with self._lock:
            for name in list(self._durable):
                self._ensure_loaded(name)
            items = list(self._tables.items())
        sql = self._engine.sql_engine
        return {name: sql.load_table(q) for name, q in items}

    def describe(self) -> Dict[str, Any]:
        out = {
            "session_id": self.session_id,
            "created_at": self.created_at,
            "idle_seconds": round(self.idle_seconds, 3),
            "ttl": self.ttl,
            "tables": self.table_names(),
        }
        with self._lock:
            if self.restored:
                out["restored"] = True
                out["tables_pending_reload"] = sorted(self._durable)
        return out


class SessionManager:
    """Session registry with lazy TTL expiry: every lookup sweeps the
    expired (closing them drops their tables, so an abandoned session
    cannot pin device memory forever). With a journal attached, creates
    and closes are journaled, and :meth:`restore` rehydrates a prior
    daemon's registry."""

    def __init__(self, engine: Any, default_ttl: float = 0.0,
                 journal: Any = None):
        self._engine = engine
        self._default_ttl = max(0.0, float(default_ttl))
        self._journal = journal
        self._sessions: Dict[str, ServeSession] = {}
        self._lock = tracked_lock(
            "serve.session.SessionManager._lock", reentrant=True
        )

    def create(self, ttl: Optional[float] = None) -> ServeSession:
        session = ServeSession(
            self._engine,
            ttl=self._default_ttl if ttl is None else float(ttl),
            journal=self._journal,
        )
        with self._lock:
            self._sessions[session.session_id] = session
        if self._journal is not None:
            self._journal.record_session(session)
        self.sweep()
        return session

    def restore(self, journaled: Dict[str, Dict[str, Any]]) -> int:
        """Rehydrate journaled sessions after a restart, skipping the
        ones whose TTL expired while the daemon was down (their journal
        records and artifacts are cleaned up). Returns the restored
        count."""
        restored = 0
        now = time.time()
        for sid, rec in sorted(journaled.items()):
            ttl = float(rec.get("ttl", 0.0) or 0.0)
            last_used = float(rec.get("last_used") or rec.get("created_at") or now)
            if ttl > 0 and now - last_used > ttl:
                # expired while down: clean up like a normal expiry
                dead = ServeSession.restore(
                    self._engine, self._journal, sid, rec
                )
                dead.close(forget=True)
                continue
            session = ServeSession.restore(
                self._engine, self._journal, sid, rec
            )
            with self._lock:
                self._sessions[sid] = session
            restored += 1
        return restored

    def adopt(
        self, journaled: Dict[str, Dict[str, Any]]
    ) -> Tuple[List[str], int]:
        """Fleet failover: rehydrate ANOTHER replica's journaled
        sessions into this manager, importing each adopted record into
        OUR journal so the sessions survive this daemon's own restarts
        too. Sessions whose TTL lapsed are cleaned up exactly like
        :meth:`restore`'s expiry path; ids already live here are left
        untouched (the local session is the current owner). Returns
        (adopted session ids, expired count)."""
        adopted: List[str] = []
        expired = 0
        now = time.time()
        for sid, rec in sorted(journaled.items()):
            with self._lock:
                exists = sid in self._sessions
            if exists:
                self._engine.log.warning(
                    "fugue_tpu serve: adoption skipped session %s — a "
                    "live local session already owns the id", sid,
                )
                continue
            ttl = float(rec.get("ttl", 0.0) or 0.0)
            last_used = float(
                rec.get("last_used") or rec.get("created_at") or now
            )
            session = ServeSession.restore(
                self._engine, self._journal, sid, rec
            )
            if ttl > 0 and now - last_used > ttl:
                session.close(forget=True)
                expired += 1
                continue
            with self._lock:
                self._sessions[sid] = session
            if self._journal is not None:
                self._journal.import_session(sid, rec)
            adopted.append(sid)
        return adopted, expired

    def get(self, session_id: str) -> ServeSession:
        """Raises ``KeyError`` for unknown AND expired ids (an expired
        session is closed on discovery)."""
        self.sweep()
        with self._lock:
            session = self._sessions.get(session_id)
        if session is None:
            raise KeyError(f"unknown or expired session {session_id}")
        session.touch()
        return session

    def peek(self, session_id: str) -> Optional[ServeSession]:
        """The live session WITHOUT touching it (no TTL refresh, no
        sweep) — how the daemon's view sweep checks liveness without
        keeping an abandoned session alive forever."""
        with self._lock:
            session = self._sessions.get(session_id)
        return None if session is None or session.expired else session

    def close(self, session_id: str) -> List[str]:
        with self._lock:
            session = self._sessions.pop(session_id, None)
        if session is None:
            raise KeyError(f"unknown or expired session {session_id}")
        return session.close(forget=True)

    def close_all(self) -> None:
        """User-facing teardown: closes AND forgets every session."""
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close(forget=True)

    def shutdown(self) -> None:
        """Daemon shutdown: drop the catalog copies (the engine is
        dying) but KEEP the journal records and artifacts — the next
        daemon on this state path rehydrates them."""
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
        for s in sessions:
            s.close(forget=False)

    def sweep(self) -> int:
        """Close every expired session; returns how many were closed.
        Chaos site ``serve.sweep`` fires per expired session — an
        injected fault leaves that session for the next sweep instead
        of wedging the caller."""
        with self._lock:
            expired = [
                (sid, s) for sid, s in self._sessions.items() if s.expired
            ]
            for sid, _ in expired:
                del self._sessions[sid]
        closed = 0
        for sid, s in expired:
            try:
                fault_point("serve.sweep", sid)
                s.close(forget=True)
                closed += 1
            except Exception as ex:
                # put it back: the tables are still live, so the session
                # must stay discoverable until a sweep succeeds
                with self._lock:
                    self._sessions.setdefault(sid, s)
                self._engine.log.warning(
                    "fugue_tpu serve: sweep of expired session %s failed "
                    "(%s: %s); retrying next sweep",
                    sid, type(ex).__name__, ex,
                )
        return closed

    def count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def integrity_rejected(self) -> int:
        with self._lock:
            return sum(s.integrity_rejected for s in self._sessions.values())

    def describe(self) -> List[Dict[str, Any]]:
        with self._lock:
            sessions = list(self._sessions.values())
        return [s.describe() for s in sessions]
