"""Conditional-dispatch plugin system.

The extensibility backbone (role of reference ``fugue/_utils/registry.py:9``
``fugue_plugin`` + the ``"fugue.plugins"`` entry point protocol, rebuilt from
scratch): a function decorated with :func:`fugue_tpu_plugin` becomes a
dispatcher; implementations register with ``@f.candidate(matcher)`` where
``matcher(*args, **kwargs) -> bool`` decides applicability. Candidates are
tried in priority order (highest first, later registrations win ties); if none
matches, the decorated body runs as the fallback.
"""

import inspect
from importlib.metadata import entry_points
from typing import Any, Callable, List, NamedTuple, Optional

_ENTRY_POINT_GROUP = "fugue_tpu.plugins"
_PLUGINS_LOADED = False


class _Candidate(NamedTuple):
    matcher: Callable[..., bool]
    func: Callable
    priority: float
    order: int


class ConditionalDispatcher:
    def __init__(self, default_func: Callable):
        self._default = default_func
        self._candidates: List[_Candidate] = []
        self._counter = 0
        self.__name__ = default_func.__name__
        self.__doc__ = default_func.__doc__
        self.__module__ = default_func.__module__
        try:
            self.__signature__ = inspect.signature(default_func)
        except (TypeError, ValueError):
            pass

    def candidate(
        self, matcher: Callable[..., bool], priority: float = 1.0
    ) -> Callable[[Callable], Callable]:
        def deco(func: Callable) -> Callable:
            self.register(matcher, func, priority)
            return func

        return deco

    def register(
        self, matcher: Callable[..., bool], func: Callable, priority: float = 1.0
    ) -> None:
        self._counter += 1
        self._candidates.append(_Candidate(matcher, func, priority, self._counter))
        # stable: higher priority first; among equal priorities, later wins
        self._candidates.sort(key=lambda c: (-c.priority, -c.order))

    def unregister(self, func: Callable) -> None:
        """Remove every candidate backed by ``func`` (tests and
        temporary registrations)."""
        self._candidates = [c for c in self._candidates if c.func is not func]

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        _load_entry_point_plugins()
        for c in self._candidates:
            try:
                matched = c.matcher(*args, **kwargs)
            except Exception:
                matched = False
            if matched:
                return c.func(*args, **kwargs)
        return self._default(*args, **kwargs)

    def run_top(self, *args: Any, **kwargs: Any) -> Any:
        """Like __call__ but raises NotImplementedError when nothing matches
        and the default body raises."""
        return self(*args, **kwargs)


def fugue_tpu_plugin(func: Callable) -> ConditionalDispatcher:
    return ConditionalDispatcher(func)


# keep the short alias used across the codebase
fugue_plugin = fugue_tpu_plugin


def _load_entry_point_plugins() -> None:
    """Load third-party plugin modules registered under the
    ``fugue_tpu.plugins`` entry point group (parity with the reference's
    ``fugue.plugins`` group, reference setup.py:96-108)."""
    global _PLUGINS_LOADED
    if _PLUGINS_LOADED:
        return
    _PLUGINS_LOADED = True
    try:
        eps = entry_points(group=_ENTRY_POINT_GROUP)
    except TypeError:  # older API
        eps = entry_points().get(_ENTRY_POINT_GROUP, [])  # type: ignore
    for ep in eps:
        try:
            ep.load()
        except Exception:  # plugin failures never break the host
            pass
