"""fugue_tpu: a TPU-native unified interface for distributed dataframe computing.

A ground-up rebuild of the capabilities of Fugue (reference: guilhermedelyra/fugue)
designed TPU-first: the flagship execution backend stores dataframe partitions as
sharded ``jax.Array`` blocks on a device mesh and compiles transformers with
``shard_map``/``vmap``, while the framework core (schema-carrying DataFrames,
``PartitionSpec``, ExecutionEngine facets, interfaceless transformers, a lazy
workflow DAG and a SQL front end) is self-contained pure Python.
"""

__version__ = "0.1.0"

from fugue_tpu.schema import Schema
from fugue_tpu.constants import register_global_conf
from fugue_tpu.collections.partition import PartitionSpec, PartitionCursor
from fugue_tpu.collections.yielded import PhysicalYielded, Yielded
from fugue_tpu.dataset import Dataset
from fugue_tpu.dataframe import (
    ArrayDataFrame,
    ArrowDataFrame,
    DataFrame,
    DataFrames,
    IterableArrowDataFrame,
    IterableDataFrame,
    IterablePandasDataFrame,
    LocalBoundedDataFrame,
    LocalDataFrame,
    LocalDataFrameIterableDataFrame,
    PandasDataFrame,
    as_fugue_df,
)
from fugue_tpu.bag import ArrayBag, Bag
from fugue_tpu.execution import (
    AnyDataFrame,
    ExecutionEngine,
    MapEngine,
    NativeExecutionEngine,
    SQLEngine,
    clear_global_engine,
    engine_context,
    make_execution_engine,
    register_default_execution_engine,
    register_execution_engine,
    register_sql_engine,
    set_global_engine,
)

from fugue_tpu.extensions import (
    CoTransformer,
    Creator,
    OutputCoTransformer,
    Outputter,
    OutputTransformer,
    Processor,
    Transformer,
    cotransformer,
    creator,
    output_cotransformer,
    output_transformer,
    outputter,
    processor,
    register_creator,
    register_output_transformer,
    register_outputter,
    register_processor,
    register_transformer,
    transformer,
)
from fugue_tpu.rpc import (
    EmptyRPCHandler,
    RPCClient,
    RPCFunc,
    RPCHandler,
    RPCServer,
    make_rpc_server,
    to_rpc_handler,
)
from fugue_tpu.workflow import (
    FugueWorkflow,
    FugueWorkflowResult,
    WorkflowDataFrame,
    module,
)
from fugue_tpu.workflow.api import explain, out_transform, raw_sql, transform
from fugue_tpu.sql_frontend.api import (  # noqa: E402
    explain_sql,
    fugue_sql,
    fugue_sql_flow,
    lint_sql,
)

import fugue_tpu.registry  # noqa: F401  (registers builtin engines)
