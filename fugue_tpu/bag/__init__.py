from fugue_tpu.bag.bag import Bag, BagDisplay, LocalBag, LocalBoundedBag
from fugue_tpu.bag.array_bag import ArrayBag
