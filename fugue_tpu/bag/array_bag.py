from typing import Any, Iterable, List

from fugue_tpu.bag.bag import Bag, LocalBoundedBag
from fugue_tpu.utils.assertion import assert_or_throw


class ArrayBag(LocalBoundedBag):
    def __init__(self, data: Any, copy: bool = True):
        super().__init__()
        if isinstance(data, ArrayBag):
            self._native: List[Any] = list(data._native) if copy else data._native
        elif isinstance(data, list):
            self._native = list(data) if copy else data
        elif isinstance(data, Iterable):
            self._native = list(data)
        else:
            raise ValueError(f"can't initialize ArrayBag with {type(data)}")

    @property
    def native(self) -> List[Any]:
        return self._native

    @property
    def empty(self) -> bool:
        return len(self._native) == 0

    def count(self) -> int:
        return len(self._native)

    def peek(self) -> Any:
        assert_or_throw(not self.empty, ValueError("bag is empty"))
        return self._native[0]

    def as_array(self) -> List[Any]:
        return list(self._native)
