"""Bag: unordered collection of arbitrary python objects — the schemaless
sibling of DataFrame (reference fugue/bag/bag.py:7)."""

from abc import abstractmethod
from typing import Any, List, Optional

from fugue_tpu.dataset.dataset import Dataset, DatasetDisplay, get_dataset_display
from fugue_tpu.utils.assertion import assert_or_throw


class Bag(Dataset):
    @abstractmethod
    def as_local_bounded(self) -> "LocalBoundedBag":  # pragma: no cover
        raise NotImplementedError

    def as_local(self) -> "LocalBag":
        return self.as_local_bounded()

    @abstractmethod
    def peek(self) -> Any:  # pragma: no cover - interface
        raise NotImplementedError

    @abstractmethod
    def as_array(self) -> List[Any]:  # pragma: no cover - interface
        raise NotImplementedError

    def head(self, n: int) -> "LocalBoundedBag":
        from fugue_tpu.bag.array_bag import ArrayBag

        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        return ArrayBag(self.as_array()[:n])


class LocalBag(Bag):
    @property
    def is_local(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return 1


class LocalBoundedBag(LocalBag):
    @property
    def is_bounded(self) -> bool:
        return True

    def as_local_bounded(self) -> "LocalBoundedBag":
        return self


class BagDisplay(DatasetDisplay):
    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:
        bg: Bag = self._ds  # type: ignore
        head = bg.head(n).as_array()
        if title:
            print(title)
        print(type(bg).__name__)
        print(head)
        if with_count:
            print(f"Total count: {bg.count()}")


@get_dataset_display.candidate(lambda ds: isinstance(ds, Bag), priority=0.5)
def _get_bag_display(ds: Bag) -> BagDisplay:
    return BagDisplay(ds)
