"""RPC: the worker->driver callback channel (reference fugue/rpc/base.py).

Handlers live on the driver; the server hands out picklable clients that are
shipped to workers inside map closures; ``client(*args)`` invokes the handler
on the driver. ``NativeRPCServer`` is in-process (local engines and the jax
single-controller model); ``fugue_tpu.rpc.http`` provides a stdlib-HTTP server
for true multi-host setups (flask replacement)."""

import pickle
from abc import ABC, abstractmethod
from threading import RLock
from typing import Any, Callable, Dict, Optional
from uuid import uuid4

from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.params import ParamDict


class RPCClient:
    """Callable handle a worker invokes to reach a driver-side handler."""

    def __call__(self, *args: Any, **kwargs: Any) -> Any:  # pragma: no cover
        raise NotImplementedError


class RPCHandler(RPCClient):
    """Driver-side handler. Subclasses implement ``__call__``."""

    def __init__(self):
        self._rpchandler_lock = RLock()
        self._running = 0

    @property
    def running(self) -> bool:
        return self._running > 0

    def start_handler(self) -> None:  # pragma: no cover - hook
        pass

    def stop_handler(self) -> None:  # pragma: no cover - hook
        pass

    def start(self) -> "RPCHandler":
        with self._rpchandler_lock:
            if self._running == 0:
                self.start_handler()
            self._running += 1
        return self

    def stop(self) -> None:
        with self._rpchandler_lock:
            if self._running == 1:
                self.stop_handler()
            self._running = max(0, self._running - 1)

    def __enter__(self) -> "RPCHandler":
        assert_or_throw(self._running > 0, ValueError("handler not started"))
        return self

    def __exit__(self, *args: Any) -> None:
        self.stop()

    def __getstate__(self) -> Any:
        raise pickle.PicklingError(f"{self} is not serializable")


class EmptyRPCHandler(RPCHandler):
    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError("empty rpc handler")


class RPCFunc(RPCHandler):
    """Wrap a plain callable as a handler."""

    def __init__(self, func: Callable):
        super().__init__()
        assert_or_throw(callable(func), ValueError(f"{func} is not callable"))
        self._func = func

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._func(*args, **kwargs)


def to_rpc_handler(obj: Any) -> RPCHandler:
    if obj is None:
        return EmptyRPCHandler()
    if isinstance(obj, RPCHandler):
        return obj
    if callable(obj):
        return RPCFunc(obj)
    raise ValueError(f"{obj} can't be converted to RPCHandler")


class RPCServer(RPCHandler, ABC):
    """Registers handlers by key and makes shippable clients (reference
    rpc/base.py:105-175)."""

    def __init__(self, conf: Any = None):
        super().__init__()
        self._conf = ParamDict(conf)
        self._handlers: Dict[str, RPCHandler] = {}

    @property
    def conf(self) -> ParamDict:
        return self._conf

    @abstractmethod
    def make_client(self, handler: Any) -> RPCClient:  # pragma: no cover
        raise NotImplementedError

    def start_server(self) -> None:  # pragma: no cover - hook
        pass

    def stop_server(self) -> None:  # pragma: no cover - hook
        pass

    def start_handler(self) -> None:
        self.start_server()

    def stop_handler(self) -> None:
        self.stop_server()
        for h in list(self._handlers.values()):
            h.stop()
        self._handlers.clear()

    def invoke(self, key: str, *args: Any, **kwargs: Any) -> Any:
        with self._rpchandler_lock:
            handler = self._handlers[key]
        return handler(*args, **kwargs)

    def register(self, handler: Any) -> str:
        key = "_" + str(uuid4())[:8]
        with self._rpchandler_lock:
            assert_or_throw(key not in self._handlers, ValueError(f"dup key {key}"))
            self._handlers[key] = to_rpc_handler(handler).start()
        return key

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError("RPCServer is not directly callable")


class NativeRPCClient(RPCClient):
    """In-process client: holds the server by reference (picklable within a
    single process; shipped across processes only by http server clients)."""

    def __init__(self, server: "NativeRPCServer", key: str):
        self._key = key
        self._server = server

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._server.invoke(self._key, *args, **kwargs)

    def __getstate__(self) -> Any:
        raise pickle.PicklingError("NativeRPCClient can't be serialized")


class NativeRPCServer(RPCServer):
    def make_client(self, handler: Any) -> RPCClient:
        key = self.register(handler)
        return NativeRPCClient(self, key)


_SERVER_TYPES: Dict[str, Callable[..., RPCServer]] = {}


def register_rpc_server(name: str, factory: Callable[..., RPCServer]) -> None:
    _SERVER_TYPES[name.lower()] = factory


def make_rpc_server(conf: Any = None) -> RPCServer:
    """Build the configured server (conf key ``fugue.rpc.server``; default
    in-process native server)."""
    conf = ParamDict(conf)
    tp = conf.get("fugue.rpc.server", "native")
    if tp.lower() == "http" and "http" not in _SERVER_TYPES:
        import fugue_tpu.rpc.http  # noqa: F401 (registers "http")
    if tp.lower() in _SERVER_TYPES:
        return _SERVER_TYPES[tp.lower()](conf)
    # a fully qualified class path
    import importlib

    module, cls = tp.rsplit(".", 1)
    return getattr(importlib.import_module(module), cls)(conf)


register_rpc_server("native", lambda conf: NativeRPCServer(conf))
