"""RPC: the worker->driver callback channel (reference fugue/rpc/base.py).

Handlers live on the driver; the server hands out picklable clients that are
shipped to workers inside map closures; ``client(*args)`` invokes the handler
on the driver. ``NativeRPCServer`` is in-process (local engines and the jax
single-controller model); ``fugue_tpu.rpc.http`` provides a stdlib-HTTP server
for true multi-host setups (flask replacement)."""

import pickle
from abc import ABC, abstractmethod
from threading import RLock
from typing import Any, Callable, Dict, Optional
from uuid import uuid4

from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.params import ParamDict


class RPCClient:
    """Callable handle a worker invokes to reach a driver-side handler."""

    def __call__(self, *args: Any, **kwargs: Any) -> Any:  # pragma: no cover
        raise NotImplementedError


class RPCHandler(RPCClient):
    """Driver-side handler. Subclasses implement ``__call__``."""

    def __init__(self):
        self._rpchandler_lock = RLock()
        self._running = 0
        self._uuid_once: Optional[str] = None

    def __uuid__(self) -> str:
        """Identity folded into workflow task uuids: a task whose
        callback handler hashes identically across runs can reuse a
        deterministic checkpoint; a CHANGED callback must invalidate it.
        Default: FAIL CLOSED — a per-instance random uuid, because the
        base class cannot see subclass constructor state and a stale
        checkpoint reused for changed state is silent corruption.
        Subclasses whose identity IS deterministic override: see
        :class:`RPCFunc` (hashes the wrapped function's source) and
        :class:`EmptyRPCHandler` (stateless by definition)."""
        if self._uuid_once is None:
            self._uuid_once = str(uuid4())
        return self._uuid_once

    @property
    def running(self) -> bool:
        return self._running > 0

    def start_handler(self) -> None:  # pragma: no cover - hook
        pass

    def stop_handler(self) -> None:  # pragma: no cover - hook
        pass

    def start(self) -> "RPCHandler":
        with self._rpchandler_lock:
            if self._running == 0:
                self.start_handler()
            self._running += 1
        return self

    def stop(self) -> None:
        with self._rpchandler_lock:
            if self._running == 1:
                self.stop_handler()
            self._running = max(0, self._running - 1)

    def __enter__(self) -> "RPCHandler":
        assert_or_throw(self._running > 0, ValueError("handler not started"))
        return self

    def __exit__(self, *args: Any) -> None:
        self.stop()

    def __getstate__(self) -> Any:
        raise pickle.PicklingError(f"{self} is not serializable")


class EmptyRPCHandler(RPCHandler):
    def __uuid__(self) -> str:
        from fugue_tpu.utils.hash import to_uuid

        return to_uuid("EmptyRPCHandler")  # stateless: always identical

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError("empty rpc handler")


class RPCFunc(RPCHandler):
    """Wrap a plain callable as a handler."""

    def __init__(self, func: Callable):
        super().__init__()
        assert_or_throw(callable(func), ValueError(f"{func} is not callable"))
        self._func = func

    def __uuid__(self) -> str:
        # hash the wrapped callable by SOURCE **plus captured state** so
        # any behavioral change to the callback changes the task uuid:
        # partial args fold in, closure cells fold in, a bound method
        # folds its instance's __dict__. State that can't be hashed
        # deterministically (opaque objects — hash._normalize falls back
        # to repr with a memory address) or source that can't be read
        # (exec'd/REPL code) FAILS CLOSED into a per-run uuid:
        # recomputing is safe, reusing a stale checkpoint is not.
        import functools
        import inspect
        from fugue_tpu.utils.hash import to_uuid

        f: Any = self._func
        state: list = []
        while isinstance(f, functools.partial):
            state.append(
                (
                    _state_view(list(f.args)),
                    _state_view(sorted((f.keywords or {}).items())),
                )
            )
            f = f.func
        bound = getattr(f, "__self__", None)
        if bound is not None:
            if hasattr(bound, "__uuid__"):
                state.append(bound.__uuid__())
            else:
                try:
                    state.append(_state_view(sorted(vars(bound).items())))
                except TypeError:  # no __dict__ (slots/builtins)
                    return str(uuid4())
        f = getattr(f, "__func__", f)  # bound method -> function
        if hasattr(f, "__uuid__"):
            base: Any = f.__uuid__()
        elif inspect.isbuiltin(f):  # builtins are stable across runs
            base = to_uuid(f)
        elif inspect.isfunction(f):
            try:
                inspect.getsource(f)
            except (OSError, TypeError):
                return str(uuid4())  # source unknown: never reuse
            # the TRANSITIVE state view: closure cells, default args, and
            # the same for every captured function, recursively
            state.append(_state_view(f))
            base = "fn"
        else:
            return str(uuid4())  # opaque callable: never reuse
        if not _state_hash_is_sound(state):
            # captured state contains an opaque object whose repr may
            # hide behavior-relevant changes: never reuse
            return str(uuid4())
        return to_uuid(type(self).__name__, base, state)

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._func(*args, **kwargs)


def _state_view(v: Any, _seen: Optional[set] = None) -> Any:
    """Expand a captured-state structure so EVERY behavior-carrying leaf
    is visible to the hash: functions become (fn, [defaults, kwdefaults,
    closure-cells]) with their own captured functions expanded
    recursively — nested closures and default-argument bindings cannot
    silently escape checkpoint invalidation."""
    import inspect

    seen = _seen if _seen is not None else set()
    if inspect.isfunction(v):
        if id(v) in seen:
            return "<cycle>"
        seen.add(id(v))
        inner: list = []
        if v.__defaults__:
            inner.append(("defaults", _state_view(list(v.__defaults__), seen)))
        if v.__kwdefaults__:
            inner.append(
                ("kwdefaults", _state_view(sorted(v.__kwdefaults__.items()), seen))
            )
        if v.__closure__:
            cells = []
            for c in v.__closure__:
                try:
                    cells.append(_state_view(c.cell_contents, seen))
                except ValueError:  # still-empty cell
                    cells.append("<empty>")
            inner.append(("closure", cells))
        return (v, inner)
    if isinstance(v, (set, frozenset)):
        return [_state_view(x, seen) for x in sorted(v, key=repr)]
    if isinstance(v, (list, tuple)):
        return [_state_view(x, seen) for x in v]
    if isinstance(v, dict):
        return {str(k): _state_view(x, seen) for k, x in v.items()}
    return v


def _state_hash_is_sound(v: Any) -> bool:
    """True when every leaf of a captured-state structure hashes by
    VALUE (plain data, source-hashed functions, __uuid__ carriers) —
    anything else would hash by repr, which a custom __repr__ can make
    state-independent, silently defeating checkpoint invalidation."""
    import inspect

    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return True
    if isinstance(v, (list, tuple, set, frozenset)):
        return all(_state_hash_is_sound(x) for x in v)
    if isinstance(v, dict):
        return all(
            _state_hash_is_sound(k) and _state_hash_is_sound(x)
            for k, x in v.items()
        )
    if hasattr(v, "__uuid__"):
        return True
    if inspect.isfunction(v):
        try:
            inspect.getsource(v)
            return True
        except (OSError, TypeError):
            return False
    return False


def to_rpc_handler(obj: Any) -> RPCHandler:
    if obj is None:
        return EmptyRPCHandler()
    if isinstance(obj, RPCHandler):
        return obj
    if callable(obj):
        return RPCFunc(obj)
    raise ValueError(f"{obj} can't be converted to RPCHandler")


class RPCServer(RPCHandler, ABC):
    """Registers handlers by key and makes shippable clients (reference
    rpc/base.py:105-175)."""

    def __init__(self, conf: Any = None):
        super().__init__()
        self._conf = ParamDict(conf)
        self._handlers: Dict[str, RPCHandler] = {}

    @property
    def conf(self) -> ParamDict:
        return self._conf

    @abstractmethod
    def make_client(self, handler: Any) -> RPCClient:  # pragma: no cover
        raise NotImplementedError

    def start_server(self) -> None:  # pragma: no cover - hook
        pass

    def stop_server(self) -> None:  # pragma: no cover - hook
        pass

    def start_handler(self) -> None:
        self.start_server()

    def stop_handler(self) -> None:
        self.stop_server()
        for h in list(self._handlers.values()):
            h.stop()
        self._handlers.clear()

    def invoke(self, key: str, *args: Any, **kwargs: Any) -> Any:
        # fault-injection site: a worker->driver callback transport blip
        # ("rpc" keyed by handler key; match "*" to fault any callback)
        from fugue_tpu.testing.faults import fault_point

        fault_point("rpc", key)
        with self._rpchandler_lock:
            handler = self._handlers[key]
        return handler(*args, **kwargs)

    def register(self, handler: Any) -> str:
        key = "_" + str(uuid4())[:8]
        with self._rpchandler_lock:
            assert_or_throw(key not in self._handlers, ValueError(f"dup key {key}"))
            self._handlers[key] = to_rpc_handler(handler).start()
        return key

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError("RPCServer is not directly callable")


class NativeRPCClient(RPCClient):
    """In-process client: holds the server by reference (picklable within a
    single process; shipped across processes only by http server clients)."""

    def __init__(self, server: "NativeRPCServer", key: str):
        self._key = key
        self._server = server

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._server.invoke(self._key, *args, **kwargs)

    def __getstate__(self) -> Any:
        raise pickle.PicklingError("NativeRPCClient can't be serialized")


class NativeRPCServer(RPCServer):
    def make_client(self, handler: Any) -> RPCClient:
        key = self.register(handler)
        return NativeRPCClient(self, key)


_SERVER_TYPES: Dict[str, Callable[..., RPCServer]] = {}


def register_rpc_server(name: str, factory: Callable[..., RPCServer]) -> None:
    _SERVER_TYPES[name.lower()] = factory


def make_rpc_server(conf: Any = None) -> RPCServer:
    """Build the configured server (conf key ``fugue.rpc.server``; default
    in-process native server)."""
    conf = ParamDict(conf)
    tp = conf.get("fugue.rpc.server", "native")
    if tp.lower() == "http" and "http" not in _SERVER_TYPES:
        import fugue_tpu.rpc.http  # noqa: F401 (registers "http")
    if tp.lower() in _SERVER_TYPES:
        return _SERVER_TYPES[tp.lower()](conf)
    # a fully qualified class path
    import importlib

    module, cls = tp.rsplit(".", 1)
    return getattr(importlib.import_module(module), cls)(conf)


register_rpc_server("native", lambda conf: NativeRPCServer(conf))
