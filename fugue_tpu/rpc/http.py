"""Distributed worker->driver RPC over HTTP (stdlib; the role of
FlaskRPCServer in the reference, fugue/rpc/flask.py:19-120).

The server runs on the driver; ``make_client`` returns a PICKLABLE client
carrying only (host, port, key, timeout), so it ships inside map closures
to remote workers. The wire format is pickle over POST bodies — the same
trust model as the reference's cloudpickle-over-flask channel: this is a
private driver<->worker control plane, not a public endpoint.

Conf keys (parity with ``fugue.rpc.flask_server.*``):

- ``fugue.rpc.server = "http"``
- ``fugue.rpc.http_server.host`` (default ``127.0.0.1``)
- ``fugue.rpc.http_server.port`` (default ``0`` = ephemeral)
- ``fugue.rpc.http_server.timeout`` seconds (default ``30``)
- ``fugue.rpc.http_server.retries`` (default ``2``): bounded
  exponential-backoff retries on TRANSIENT transport failures only —
  connection refused/reset and HTTP 503 (the classifier in
  ``workflow/fault.py`` decides); any other HTTP error and every
  server-side handler error fail fast.

Daemon hardening (the serving daemon in :mod:`fugue_tpu.serve` runs this
server long-lived on a semi-trusted edge, so the handler defends itself):

- ``fugue.rpc.http_server.max_body_bytes`` (default 64 MiB): a request
  whose declared body exceeds the cap is rejected with HTTP 413 BEFORE
  the body is read into memory (0 = unlimited).
- ``fugue.rpc.http_server.read_timeout`` (default 30 s): per-request
  socket read timeout — a stalled client cannot pin a handler thread
  forever (0 = unlimited).
- handler exceptions cross the wire as a STRUCTURED payload
  (``{"error": <type name>, "message": <str(ex)>}``) — never a raw
  traceback.
"""

import logging
import pickle
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from fugue_tpu.rpc.base import (
    RPCClient,
    RPCServer,
    register_rpc_server,
)

__all__ = ["HTTPRPCServer", "HTTPRPCClient"]

_LOG = logging.getLogger("fugue_tpu.rpc")

_CONF_HOST = "fugue.rpc.http_server.host"
_CONF_PORT = "fugue.rpc.http_server.port"
_CONF_TIMEOUT = "fugue.rpc.http_server.timeout"
_CONF_RETRIES = "fugue.rpc.http_server.retries"
_CONF_MAX_BODY = "fugue.rpc.http_server.max_body_bytes"
_CONF_READ_TIMEOUT = "fugue.rpc.http_server.read_timeout"

_DEFAULT_MAX_BODY = 64 * 1024 * 1024
_DEFAULT_READ_TIMEOUT = 30.0

# HTTP statuses that mark a transient server condition worth retrying
# (503 overload/drain, 429 per-tenant caps — both are the serving
# daemon's backpressure vocabulary); everything else (404, 500 handler
# bugs, ...) is deterministic
_RETRYABLE_HTTP = (503, 429)

# an absurd Retry-After from a confused server must not park a client
# thread for minutes — cap what we are willing to honor
_MAX_RETRY_AFTER = 10.0


def parse_retry_after(headers: Any) -> Optional[float]:
    """Seconds from a ``Retry-After`` header (delta-seconds form only —
    the HTTP-date form is overkill for this control plane), capped at
    ``_MAX_RETRY_AFTER``; None when absent/unparseable. Shared by the
    RPC client below and :class:`fugue_tpu.serve.client.ServeClient`."""
    try:
        raw = headers.get("Retry-After") if headers is not None else None
        if raw is None:
            return None
        return min(max(0.0, float(raw)), _MAX_RETRY_AFTER)
    except (TypeError, ValueError):
        return None


def backoff_delay(
    attempt: int, rng: Any, server_hint: Optional[float] = None
) -> float:
    """Bounded-exponential retry delay shared by the RPC client and
    :class:`fugue_tpu.serve.client.ServeClient`: 50ms doubling with full
    jitter, capped at 2s — one backoff policy, not two drifting copies.

    A server's (already capped) ``Retry-After`` hint is a FLOOR, with
    the jittered exponential added ON TOP of it. The old policy
    (``max(delay, hint)``) made the hint an exact release time: when a
    fleet-wide overload 503s every client with the same predicted drain
    hint, they all slept the identical interval and stampeded back in
    one synchronized wave, re-triggering the very overload they were
    told to wait out. Full jitter (rng.random() scales the whole
    exponential term, not a 10% trim) spreads the herd across the
    backoff window while the hint still guarantees nobody returns
    before the server asked."""
    base = min(0.05 * (2 ** (attempt - 1)), 2.0)
    delay = base * rng.random()
    if server_hint is not None:
        delay += max(0.0, server_hint)
    return delay


def _is_transient_transport_error(ex: BaseException) -> bool:
    """Transient-vs-deterministic triage for one RPC transport failure,
    reusing the workflow fault classifier for the OS/socket layer."""
    from fugue_tpu.workflow.fault import TRANSIENT, classify_error

    if isinstance(ex, urllib.error.HTTPError):
        return ex.code in _RETRYABLE_HTTP
    if isinstance(ex, urllib.error.URLError):
        reason = ex.reason
        if isinstance(reason, BaseException):
            return classify_error(reason) == TRANSIENT
        return True  # bare-string reason: treat as a transport hiccup
    return classify_error(ex) == TRANSIENT


def structured_error(ex: BaseException) -> dict:
    """The one shape a server-side failure takes on the wire: exception
    type name + message, NEVER a traceback (frames leak file paths and
    internals to whoever is on the other end of a long-lived daemon
    socket)."""
    return {"error": type(ex).__name__, "message": str(ex)}


class HardenedRequestHandler(BaseHTTPRequestHandler):
    """Request handler base with the daemon-hardening behaviors shared by
    the RPC protocol handler below and the serving daemon's JSON API
    (:mod:`fugue_tpu.serve.http`):

    - ``timeout`` (class attr, set by the server factory from
      ``fugue.rpc.http_server.read_timeout``) is the stdlib
      StreamRequestHandler per-request socket timeout: a stalled client
      raises ``socket.timeout``, which ``handle_one_request`` turns into
      a closed connection instead of a pinned thread.
    - :meth:`read_body` enforces ``max_body`` from the declared
      Content-Length BEFORE reading, answering HTTP 413 (and closing the
      connection, since the unread body poisons keep-alive) over the cap.
    """

    # set by the server factory; None/0 = unlimited
    timeout: Any = _DEFAULT_READ_TIMEOUT
    max_body: int = _DEFAULT_MAX_BODY

    def read_body(self) -> Optional[bytes]:
        """The request body, or None when the request was rejected (the
        error response has already been written): a malformed or
        negative Content-Length answers a structured 400, a length over
        the cap answers 413 — both close the connection, since the
        unread body poisons keep-alive."""
        raw = self.headers.get("Content-Length", "0") or "0"
        try:
            length = int(raw)
            if length < 0:
                raise ValueError("negative length")
        except ValueError:
            self.close_connection = True
            self.send_error_payload(
                400, ValueError(f"bad Content-Length {raw!r}")
            )
            return None
        if self.max_body and length > self.max_body:
            self.close_connection = True
            self.send_error_payload(
                413,
                ValueError(
                    f"request body {length}B exceeds the "
                    f"{self.max_body}B cap"
                ),
            )
            return None
        return self.rfile.read(length)

    def send_error_payload(self, status: int, ex: BaseException) -> None:
        """Protocol-specific structured error writer (no tracebacks)."""
        raise NotImplementedError  # pragma: no cover - subclass contract

    def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr
        pass


class _RPCRequestHandler(HardenedRequestHandler):
    # set by the server factory
    rpc_server: "HTTPRPCServer"

    def do_POST(self) -> None:  # noqa: N802 (stdlib naming)
        body = self.read_body()  # socket.timeout propagates: stdlib
        if body is None:  # handle_one_request closes the connection
            return
        try:
            key, args, kwargs = pickle.loads(body)
            result = self.rpc_server.invoke(key, *args, **kwargs)
            payload = pickle.dumps((True, result))
        except Exception as ex:  # error crosses the wire as data
            payload = pickle.dumps((False, structured_error(ex)))
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def send_error_payload(self, status: int, ex: BaseException) -> None:
        payload = pickle.dumps((False, structured_error(ex)))
        self.send_response(status)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class HTTPRPCClient(RPCClient):
    """Picklable: carries only the address, handler key and retry
    budget. Transport failures (connection refused/reset, HTTP 503)
    retry with bounded exponential backoff + jitter; deterministic
    failures — other HTTP statuses and handler errors relayed by the
    driver — fail fast on the first attempt.

    Retries give AT-LEAST-ONCE delivery: a connection that resets after
    the request was sent may replay a handler that already ran.
    Handlers should be idempotent — the same contract the task-level
    retry layer (``fugue.workflow.retry.*``) already imposes on
    callbacks; set ``fugue.rpc.http_server.retries=0`` for handlers
    where a duplicate side effect is worse than a failed call."""

    def __init__(
        self, host: str, port: int, key: str, timeout: float,
        retries: int = 2,
    ):
        self._host = host
        self._port = port
        self._key = key
        self._timeout = timeout
        self._retries = max(0, int(retries))

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        body = pickle.dumps((self._key, args, kwargs))
        rng = random.Random()
        attempt = 0
        while True:
            attempt += 1
            try:
                return self._call_once(body)
            except Exception as ex:
                if attempt > self._retries or not _is_transient_transport_error(
                    ex
                ):
                    raise
                # a backpressure answer names its own backoff: honor the
                # server's Retry-After over our schedule
                delay = backoff_delay(
                    attempt,
                    rng,
                    parse_retry_after(ex.headers)
                    if isinstance(ex, urllib.error.HTTPError)
                    else None,
                )
                _LOG.info(
                    "fugue_tpu rpc retry %d/%d after %s: %s",
                    attempt, self._retries, type(ex).__name__, ex,
                )
                time.sleep(delay)

    def _call_once(self, body: bytes) -> Any:
        req = urllib.request.Request(
            f"http://{self._host}:{self._port}/", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=self._timeout) as resp:
            ok, payload = pickle.loads(resp.read())
        if not ok:
            if isinstance(payload, dict):  # structured handler error
                payload = f"{payload.get('error')}: {payload.get('message')}"
            raise RuntimeError(f"rpc call failed on driver: {payload}")
        return payload


class HTTPRPCServer(RPCServer):
    """Threaded stdlib HTTP server hosting the registered handlers, with
    the daemon-hardening conf applied to every request handler (body
    size cap, per-request read timeout, structured error payloads)."""

    # the protocol handler the factory binds; the serving daemon's HTTP
    # layer subclasses this server and swaps in its JSON API handler
    handler_class = _RPCRequestHandler

    def __init__(self, conf: Any = None):
        super().__init__(conf)
        self._host: str = self.conf.get(_CONF_HOST, "127.0.0.1")
        self._port: int = int(self.conf.get(_CONF_PORT, 0))
        self._timeout: float = float(self.conf.get(_CONF_TIMEOUT, 30))
        self._max_body: int = int(
            self.conf.get(_CONF_MAX_BODY, _DEFAULT_MAX_BODY)
        )
        self._read_timeout: float = float(
            self.conf.get(_CONF_READ_TIMEOUT, _DEFAULT_READ_TIMEOUT)
        )
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Any:
        """(host, actual_port) once started."""
        assert self._httpd is not None, "server not started"
        return (self._host, self._httpd.server_address[1])

    def start_server(self) -> None:
        handler = type(
            "_BoundHandler",
            (self.handler_class,),
            {
                "rpc_server": self,
                # stdlib StreamRequestHandler: None = no socket timeout
                "timeout": self._read_timeout if self._read_timeout > 0
                else None,
                "max_body": max(0, self._max_body),
            },
        )
        self._httpd = ThreadingHTTPServer((self._host, self._port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop_server(self) -> None:
        """Idempotent shutdown: safe to call repeatedly; a serve thread
        that outlives its join timeout is reported (and retried by a
        later call) instead of silently leaked."""
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            if thread.is_alive():
                _LOG.warning(
                    "fugue_tpu rpc: HTTP server thread did not stop "
                    "within 5s; shutdown is wedged (daemon thread will "
                    "not block interpreter exit)"
                )
            else:
                self._thread = None

    def make_client(self, handler: Any) -> RPCClient:
        key = self.register(handler)
        host, port = self.address
        return HTTPRPCClient(
            host, port, key, self._timeout,
            retries=int(self.conf.get(_CONF_RETRIES, 2)),
        )


register_rpc_server("http", lambda conf: HTTPRPCServer(conf))
