"""Distributed worker->driver RPC over HTTP (stdlib; the role of
FlaskRPCServer in the reference, fugue/rpc/flask.py:19-120).

The server runs on the driver; ``make_client`` returns a PICKLABLE client
carrying only (host, port, key, timeout), so it ships inside map closures
to remote workers. The wire format is pickle over POST bodies — the same
trust model as the reference's cloudpickle-over-flask channel: this is a
private driver<->worker control plane, not a public endpoint.

Conf keys (parity with ``fugue.rpc.flask_server.*``):

- ``fugue.rpc.server = "http"``
- ``fugue.rpc.http_server.host`` (default ``127.0.0.1``)
- ``fugue.rpc.http_server.port`` (default ``0`` = ephemeral)
- ``fugue.rpc.http_server.timeout`` seconds (default ``30``)
"""

import pickle
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from fugue_tpu.rpc.base import (
    RPCClient,
    RPCServer,
    register_rpc_server,
)

__all__ = ["HTTPRPCServer", "HTTPRPCClient"]

_CONF_HOST = "fugue.rpc.http_server.host"
_CONF_PORT = "fugue.rpc.http_server.port"
_CONF_TIMEOUT = "fugue.rpc.http_server.timeout"


class _RPCRequestHandler(BaseHTTPRequestHandler):
    # set by the server factory
    rpc_server: "HTTPRPCServer"

    def do_POST(self) -> None:  # noqa: N802 (stdlib naming)
        try:
            length = int(self.headers.get("Content-Length", "0"))
            key, args, kwargs = pickle.loads(self.rfile.read(length))
            result = self.rpc_server.invoke(key, *args, **kwargs)
            payload = pickle.dumps((True, result))
        except Exception as ex:  # error crosses the wire as data
            payload = pickle.dumps((False, f"{type(ex).__name__}: {ex}"))
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr
        pass


class HTTPRPCClient(RPCClient):
    """Picklable: carries only the address and handler key."""

    def __init__(self, host: str, port: int, key: str, timeout: float):
        self._host = host
        self._port = port
        self._key = key
        self._timeout = timeout

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        body = pickle.dumps((self._key, args, kwargs))
        req = urllib.request.Request(
            f"http://{self._host}:{self._port}/", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=self._timeout) as resp:
            ok, payload = pickle.loads(resp.read())
        if not ok:
            raise RuntimeError(f"rpc call failed on driver: {payload}")
        return payload


class HTTPRPCServer(RPCServer):
    """Threaded stdlib HTTP server hosting the registered handlers."""

    def __init__(self, conf: Any = None):
        super().__init__(conf)
        self._host: str = self.conf.get(_CONF_HOST, "127.0.0.1")
        self._port: int = int(self.conf.get(_CONF_PORT, 0))
        self._timeout: float = float(self.conf.get(_CONF_TIMEOUT, 30))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Any:
        """(host, actual_port) once started."""
        assert self._httpd is not None, "server not started"
        return (self._host, self._httpd.server_address[1])

    def start_server(self) -> None:
        handler = type(
            "_BoundHandler", (_RPCRequestHandler,), {"rpc_server": self}
        )
        self._httpd = ThreadingHTTPServer((self._host, self._port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop_server(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def make_client(self, handler: Any) -> RPCClient:
        key = self.register(handler)
        host, port = self.address
        return HTTPRPCClient(host, port, key, self._timeout)


register_rpc_server("http", lambda conf: HTTPRPCServer(conf))
