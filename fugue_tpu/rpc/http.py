"""Distributed worker->driver RPC over HTTP (stdlib; the role of
FlaskRPCServer in the reference, fugue/rpc/flask.py:19-120).

The server runs on the driver; ``make_client`` returns a PICKLABLE client
carrying only (host, port, key, timeout), so it ships inside map closures
to remote workers. The wire format is pickle over POST bodies — the same
trust model as the reference's cloudpickle-over-flask channel: this is a
private driver<->worker control plane, not a public endpoint.

Conf keys (parity with ``fugue.rpc.flask_server.*``):

- ``fugue.rpc.server = "http"``
- ``fugue.rpc.http_server.host`` (default ``127.0.0.1``)
- ``fugue.rpc.http_server.port`` (default ``0`` = ephemeral)
- ``fugue.rpc.http_server.timeout`` seconds (default ``30``)
- ``fugue.rpc.http_server.retries`` (default ``2``): bounded
  exponential-backoff retries on TRANSIENT transport failures only —
  connection refused/reset and HTTP 503 (the classifier in
  ``workflow/fault.py`` decides); any other HTTP error and every
  server-side handler error fail fast.
"""

import logging
import pickle
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from fugue_tpu.rpc.base import (
    RPCClient,
    RPCServer,
    register_rpc_server,
)

__all__ = ["HTTPRPCServer", "HTTPRPCClient"]

_LOG = logging.getLogger("fugue_tpu.rpc")

_CONF_HOST = "fugue.rpc.http_server.host"
_CONF_PORT = "fugue.rpc.http_server.port"
_CONF_TIMEOUT = "fugue.rpc.http_server.timeout"
_CONF_RETRIES = "fugue.rpc.http_server.retries"

# HTTP statuses that mark a transient server condition worth retrying;
# everything else (404, 500 handler bugs, ...) is deterministic
_RETRYABLE_HTTP = (503,)


def _is_transient_transport_error(ex: BaseException) -> bool:
    """Transient-vs-deterministic triage for one RPC transport failure,
    reusing the workflow fault classifier for the OS/socket layer."""
    from fugue_tpu.workflow.fault import TRANSIENT, classify_error

    if isinstance(ex, urllib.error.HTTPError):
        return ex.code in _RETRYABLE_HTTP
    if isinstance(ex, urllib.error.URLError):
        reason = ex.reason
        if isinstance(reason, BaseException):
            return classify_error(reason) == TRANSIENT
        return True  # bare-string reason: treat as a transport hiccup
    return classify_error(ex) == TRANSIENT


class _RPCRequestHandler(BaseHTTPRequestHandler):
    # set by the server factory
    rpc_server: "HTTPRPCServer"

    def do_POST(self) -> None:  # noqa: N802 (stdlib naming)
        try:
            length = int(self.headers.get("Content-Length", "0"))
            key, args, kwargs = pickle.loads(self.rfile.read(length))
            result = self.rpc_server.invoke(key, *args, **kwargs)
            payload = pickle.dumps((True, result))
        except Exception as ex:  # error crosses the wire as data
            payload = pickle.dumps((False, f"{type(ex).__name__}: {ex}"))
        self.send_response(200)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, fmt: str, *args: Any) -> None:  # silence stderr
        pass


class HTTPRPCClient(RPCClient):
    """Picklable: carries only the address, handler key and retry
    budget. Transport failures (connection refused/reset, HTTP 503)
    retry with bounded exponential backoff + jitter; deterministic
    failures — other HTTP statuses and handler errors relayed by the
    driver — fail fast on the first attempt.

    Retries give AT-LEAST-ONCE delivery: a connection that resets after
    the request was sent may replay a handler that already ran.
    Handlers should be idempotent — the same contract the task-level
    retry layer (``fugue.workflow.retry.*``) already imposes on
    callbacks; set ``fugue.rpc.http_server.retries=0`` for handlers
    where a duplicate side effect is worse than a failed call."""

    def __init__(
        self, host: str, port: int, key: str, timeout: float,
        retries: int = 2,
    ):
        self._host = host
        self._port = port
        self._key = key
        self._timeout = timeout
        self._retries = max(0, int(retries))

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        body = pickle.dumps((self._key, args, kwargs))
        rng = random.Random()
        attempt = 0
        while True:
            attempt += 1
            try:
                return self._call_once(body)
            except Exception as ex:
                if attempt > self._retries or not _is_transient_transport_error(
                    ex
                ):
                    raise
                delay = 0.05 * (2 ** (attempt - 1)) * (1.0 + rng.random() * 0.1)
                _LOG.info(
                    "fugue_tpu rpc retry %d/%d after %s: %s",
                    attempt, self._retries, type(ex).__name__, ex,
                )
                time.sleep(min(delay, 2.0))

    def _call_once(self, body: bytes) -> Any:
        req = urllib.request.Request(
            f"http://{self._host}:{self._port}/", data=body, method="POST"
        )
        with urllib.request.urlopen(req, timeout=self._timeout) as resp:
            ok, payload = pickle.loads(resp.read())
        if not ok:
            raise RuntimeError(f"rpc call failed on driver: {payload}")
        return payload


class HTTPRPCServer(RPCServer):
    """Threaded stdlib HTTP server hosting the registered handlers."""

    def __init__(self, conf: Any = None):
        super().__init__(conf)
        self._host: str = self.conf.get(_CONF_HOST, "127.0.0.1")
        self._port: int = int(self.conf.get(_CONF_PORT, 0))
        self._timeout: float = float(self.conf.get(_CONF_TIMEOUT, 30))
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Any:
        """(host, actual_port) once started."""
        assert self._httpd is not None, "server not started"
        return (self._host, self._httpd.server_address[1])

    def start_server(self) -> None:
        handler = type(
            "_BoundHandler", (_RPCRequestHandler,), {"rpc_server": self}
        )
        self._httpd = ThreadingHTTPServer((self._host, self._port), handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def stop_server(self) -> None:
        """Idempotent shutdown: safe to call repeatedly; a serve thread
        that outlives its join timeout is reported (and retried by a
        later call) instead of silently leaked."""
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            if thread.is_alive():
                _LOG.warning(
                    "fugue_tpu rpc: HTTP server thread did not stop "
                    "within 5s; shutdown is wedged (daemon thread will "
                    "not block interpreter exit)"
                )
            else:
                self._thread = None

    def make_client(self, handler: Any) -> RPCClient:
        key = self.register(handler)
        host, port = self.address
        return HTTPRPCClient(
            host, port, key, self._timeout,
            retries=int(self.conf.get(_CONF_RETRIES, 2)),
        )


register_rpc_server("http", lambda conf: HTTPRPCServer(conf))
