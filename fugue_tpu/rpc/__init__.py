from fugue_tpu.rpc.base import (
    EmptyRPCHandler,
    RPCClient,
    RPCFunc,
    RPCHandler,
    RPCServer,
    NativeRPCClient,
    NativeRPCServer,
    make_rpc_server,
    register_rpc_server,
    to_rpc_handler,
)
