import json
from typing import Any, Dict, Iterable, Optional, Tuple, Type, TypeVar, Union, no_type_check

T = TypeVar("T")

_BOOL_TRUE = {"true", "yes", "1", "on"}
_BOOL_FALSE = {"false", "no", "0", "off"}


def to_bool(obj: Any) -> bool:
    if isinstance(obj, bool):
        return obj
    if isinstance(obj, (int, float)):
        return obj != 0
    if isinstance(obj, str):
        low = obj.strip().lower()
        if low in _BOOL_TRUE:
            return True
        if low in _BOOL_FALSE:
            return False
    raise ValueError(f"can't convert {obj!r} to bool")


def _convert(value: Any, ttype: Type[T]) -> T:
    if ttype is object or ttype is Any:  # type: ignore
        return value
    if isinstance(value, ttype):
        return value  # type: ignore
    if ttype is bool:
        return to_bool(value)  # type: ignore
    if ttype is int:
        if isinstance(value, str):
            return int(value.strip())  # type: ignore
        if isinstance(value, float) and value.is_integer():
            return int(value)  # type: ignore
        raise ValueError(f"can't convert {value!r} to int")
    if ttype is float:
        if isinstance(value, (int, str)):
            return float(value)  # type: ignore
        raise ValueError(f"can't convert {value!r} to float")
    if ttype is str:
        return str(value)  # type: ignore
    if issubclass(ttype, dict) and isinstance(value, dict):
        return ttype(value)  # type: ignore
    if issubclass(ttype, list) and isinstance(value, (list, tuple)):
        return ttype(value)  # type: ignore
    raise ValueError(f"can't convert {value!r} to {ttype}")


class ParamDict(Dict[str, Any]):
    """A string-keyed dict with typed getters, the uniform bag for configs and
    extension parameters across the framework.

    Accepts a dict, an iterable of key/value tuples, or another ParamDict.
    """

    OVERWRITE = 0
    THROW = 1
    IGNORE = 2

    def __init__(self, data: Any = None, deep: bool = True):
        super().__init__()
        self.update(data, deep=deep)

    @no_type_check
    def update(  # type: ignore[override]
        self, other: Any = None, on_dup: int = 0, deep: bool = True
    ) -> "ParamDict":
        if other is None:
            return self
        if isinstance(other, dict):
            items: Iterable[Tuple[Any, Any]] = other.items()
        elif isinstance(other, Iterable):
            items = other
        else:
            raise ValueError(f"{other!r} is not iterable or a dict")
        for k, v in items:
            if not isinstance(k, str):
                raise ValueError(f"key {k!r} is not a string")
            if k in self:
                if on_dup == ParamDict.THROW:
                    raise KeyError(f"duplicated key {k}")
                if on_dup == ParamDict.IGNORE:
                    continue
            if deep and isinstance(v, dict):
                v = dict(v)
            super().__setitem__(k, v)
        return self

    def get(self, key: Union[int, str], default: T) -> T:  # type: ignore[override]
        """Typed get: the result is converted to ``type(default)``; missing key
        returns ``default``."""
        key = self._resolve_key(key, must_exist=False)
        if key is None or key not in self:
            if default is None:
                return None  # type: ignore
            return default
        value = self[key]
        if default is None:
            return value
        return _convert(value, type(default))

    def get_or_none(self, key: Union[int, str], ttype: Type[T]) -> Optional[T]:
        key = self._resolve_key(key, must_exist=False)
        if key is None or key not in self:
            return None
        return _convert(self[key], ttype)

    def get_or_throw(self, key: Union[int, str], ttype: Type[T]) -> T:
        key = self._resolve_key(key, must_exist=True)
        return _convert(self[key], ttype)

    def _resolve_key(self, key: Union[int, str], must_exist: bool) -> Optional[str]:
        if isinstance(key, int):
            keys = list(self.keys())
            if 0 <= key < len(keys):
                return keys[key]
            if must_exist:
                raise KeyError(f"index {key} out of range")
            return None
        if must_exist and key not in self:
            raise KeyError(f"{key} not found")
        return key

    def to_json(self, indent: bool = False) -> str:
        return json.dumps(self, indent=4 if indent else None)
