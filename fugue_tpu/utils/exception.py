"""Error-trace surgery: prune framework frames from tracebacks and point the
user at their own call site (reference fugue/_utils/exception.py:7-42 +
workflow.py:1586-1604 behavior). jax/XLA tracebacks are notoriously deep —
this keeps workflow failures readable."""

import traceback
from types import TracebackType
from typing import List, Optional


def prune_traceback(
    tb: Optional[TracebackType], hide_prefixes: List[str]
) -> Optional[TracebackType]:
    """Drop frames whose module file matches any hide prefix (by module name
    or path fragment). Always keeps at least the deepest frame."""
    frames: List[TracebackType] = []
    cur = tb
    while cur is not None:
        frames.append(cur)
        cur = cur.tb_next
    kept = [
        f
        for f in frames
        if not _is_hidden(f, hide_prefixes)
    ]
    if len(kept) == 0:
        kept = frames[-1:]
    # rebuild the chain from the end
    next_tb: Optional[TracebackType] = None
    for f in reversed(kept):
        next_tb = TracebackType(
            next_tb, f.tb_frame, f.tb_lasti, f.tb_lineno
        )
    return next_tb


def _match_module(module: str, prefix: str) -> bool:
    """True when ``module`` IS the package named by ``prefix`` or a submodule
    of it — 'fugue_tpu.' must not hide 'fugue_tpu_userlib.x'."""
    p = prefix.rstrip(".")
    return module == p or module.startswith(p + ".")


def _is_hidden(tb: TracebackType, prefixes: List[str]) -> bool:
    g = tb.tb_frame.f_globals
    module = g.get("__name__", "")
    return any(_match_module(module, p) for p in prefixes if p != "")


def extract_user_callsite(inject: int, hide_prefixes: List[str]) -> List[str]:
    """Capture the current stack's last ``inject`` user (non-framework)
    frames as display strings, for splicing into runtime errors."""
    if inject <= 0:
        return []
    pkg_dirs = [
        "/" + p.rstrip(".").replace(".", "/") + "/" for p in hide_prefixes if p
    ]
    frames: List[List[str]] = []  # each entry: [header, code?] of one frame
    for frame in reversed(traceback.extract_stack()[:-1]):
        fname = frame.filename.replace("\\", "/")
        if any(d in fname for d in pkg_dirs) or "/fugue_tpu/" in fname:
            continue
        entry = [f'  File "{frame.filename}", line {frame.lineno}, in {frame.name}']
        if frame.line:
            entry.append(f"    {frame.line}")
        frames.append(entry)
        if len(frames) >= inject:
            break
    res: List[str] = []
    for entry in reversed(frames):  # reverse frame ORDER, keep header/code pairs
        res.extend(entry)
    return res
