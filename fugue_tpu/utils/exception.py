"""Error-trace surgery: prune framework frames from tracebacks and point the
user at their own call site (reference fugue/_utils/exception.py:7-42 +
workflow.py:1586-1604 behavior). jax/XLA tracebacks are notoriously deep —
this keeps workflow failures readable.

Callsite attribution itself lives in :mod:`fugue_tpu.utils.callsite` (it is
shared with the static analyzer); ``extract_user_callsite`` is re-exported
here for pre-refactor importers."""

from types import TracebackType
from typing import List, Optional

from fugue_tpu.utils.callsite import (  # noqa: F401  (re-export)
    extract_user_callsite,
    package_dir as _package_dir,
)


def prune_traceback(
    tb: Optional[TracebackType], hide_prefixes: List[str]
) -> Optional[TracebackType]:
    """Drop frames whose module file matches any hide prefix (by module name
    or path fragment). Always keeps at least the deepest frame."""
    frames: List[TracebackType] = []
    cur = tb
    while cur is not None:
        frames.append(cur)
        cur = cur.tb_next
    kept = [
        f
        for f in frames
        if not _is_hidden(f, hide_prefixes)
    ]
    if len(kept) == 0:
        kept = frames[-1:]
    # rebuild the chain from the end
    next_tb: Optional[TracebackType] = None
    for f in reversed(kept):
        next_tb = TracebackType(
            next_tb, f.tb_frame, f.tb_lasti, f.tb_lineno
        )
    return next_tb


def _match_module(module: str, prefix: str) -> bool:
    """True when ``module`` IS the package named by ``prefix`` or a submodule
    of it — 'fugue_tpu.' must not hide 'fugue_tpu_userlib.x'."""
    p = prefix.rstrip(".")
    return module == p or module.startswith(p + ".")


def _is_hidden(tb: TracebackType, prefixes: List[str]) -> bool:
    g = tb.tb_frame.f_globals
    module = g.get("__name__", "")
    return any(_match_module(module, p) for p in prefixes if p != "")


def add_error_note(ex: BaseException, note: str) -> None:
    """Attach a PEP-678 note to an exception, portably: ``add_note`` on
    3.11+, a hand-rolled ``__notes__`` list on 3.10 (programmatically
    identical — 3.10 tracebacks just don't render it, which is why the
    aggregated WorkflowRuntimeError also embeds callsites in its
    message)."""
    try:
        add = getattr(ex, "add_note", None)
        if add is not None:
            add(note)
            return
        notes = getattr(ex, "__notes__", None)
        if not isinstance(notes, list):
            notes = []
            ex.__notes__ = notes  # type: ignore[attr-defined]
        notes.append(note)
    except Exception:  # pragma: no cover - never mask the original error
        pass
