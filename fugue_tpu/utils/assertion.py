from typing import Any, Callable, Union


def assert_or_throw(
    cond: bool, exception: Union[None, str, Exception, Callable[[], Any]] = None
) -> None:
    """Raise when ``cond`` is falsy.

    ``exception`` may be a message string (raises ``AssertionError``), an
    exception instance, or a zero-arg callable evaluated lazily (so building
    expensive messages costs nothing on the happy path).
    """
    if cond:
        return
    if exception is None:
        raise AssertionError()
    if callable(exception) and not isinstance(exception, Exception):
        exception = exception()
    if isinstance(exception, Exception):
        raise exception
    raise AssertionError(str(exception))
