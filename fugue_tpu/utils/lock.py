from threading import RLock
from typing import Any


class SerializableRLock:
    """An ``RLock`` that survives pickling (the lock state itself is not
    serialized; a fresh lock is created on deserialization). Engines and
    lazily-evaluated schemas hold one of these so they can be shipped to
    workers inside closures.
    """

    def __init__(self) -> None:
        self._lock = RLock()

    def __enter__(self) -> Any:
        return self._lock.__enter__()

    def __exit__(self, *args: Any, **kwargs: Any) -> Any:
        return self._lock.__exit__(*args, **kwargs)

    def __getstate__(self) -> dict:
        return {}

    def __setstate__(self, state: dict) -> None:
        self._lock = RLock()
