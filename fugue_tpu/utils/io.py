"""Host IO: parquet/csv/json load & save on local paths (reference
fugue/_utils/io.py rebuilt on pyarrow only — no fs/duckdb deps).

Files may be single files or directories of part files (the distributed
convention); saving with ``force_single`` writes one file, otherwise engines
may write a directory."""

import os
import shutil
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from fugue_tpu.dataframe import ArrowDataFrame, DataFrame, LocalBoundedDataFrame
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw

_FORMATS = {".parquet": "parquet", ".csv": "csv", ".json": "json"}


def infer_format(path: str, format_hint: Optional[str] = None) -> str:
    if format_hint is not None:
        assert_or_throw(
            format_hint in ("parquet", "csv", "json"),
            NotImplementedError(f"invalid format {format_hint}"),
        )
        return format_hint
    for suffix, fmt in _FORMATS.items():
        if path.endswith(suffix):
            return fmt
    raise NotImplementedError(f"can't infer format of {path}")


def _part_files(path: str, fmt: str) -> List[str]:
    if os.path.isdir(path):
        files = sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if not f.startswith(".") and not f.startswith("_")
        )
        assert_or_throw(len(files) > 0, FileNotFoundError(f"no part files in {path}"))
        return files
    assert_or_throw(os.path.exists(path), FileNotFoundError(path))
    return [path]


def load_df(
    path: Union[str, List[str]],
    format_hint: Optional[str] = None,
    columns: Any = None,
    **kwargs: Any,
) -> LocalBoundedDataFrame:
    paths = [path] if isinstance(path, str) else list(path)
    fmt = infer_format(paths[0], format_hint)
    tables = []
    for p in paths:
        if fmt == "parquet" and os.path.isdir(p):
            # dataset read: flat part dirs AND hive-partitioned layouts
            # (partition columns are restored from the directory names)
            cols = columns if isinstance(columns, list) else None
            t = pq.read_table(p, columns=cols, **kwargs)
            # hive partition keys arrive dictionary-encoded; decode to
            # plain types (our schema language has no dictionary type)
            for i, f in enumerate(t.schema):
                if pa.types.is_dictionary(f.type):
                    t = t.set_column(
                        i, f.name, t.column(i).cast(f.type.value_type)
                    )
            tables.append(t)
            continue
        for f in _part_files(p, fmt):
            # copy kwargs: the csv branch pops options, every file must see them
            tables.append(_load_single(f, fmt, columns, dict(kwargs)))
    table = tables[0] if len(tables) == 1 else pa.concat_tables(tables)
    if isinstance(columns, str):  # schema expression: select + cast
        schema = Schema(columns)
        from fugue_tpu.dataframe.arrow_utils import cast_table

        table = cast_table(table.select(schema.names), schema)
        return ArrowDataFrame(table, schema)
    return ArrowDataFrame(table)


def _load_single(
    path: str, fmt: str, columns: Any, kwargs: Dict[str, Any]
) -> pa.Table:
    cols = columns if isinstance(columns, list) else None
    if fmt == "parquet":
        return pq.read_table(path, columns=cols, **kwargs)
    if fmt == "csv":
        header = bool(kwargs.pop("header", True))
        infer = bool(kwargs.pop("infer_schema", False))
        schema: Optional[Schema] = None
        read_opts = pacsv.ReadOptions()
        convert_opts = pacsv.ConvertOptions()
        if isinstance(columns, str):
            assert_or_throw(
                not infer,
                ValueError(
                    "can't set typed columns together with infer_schema=True"
                ),
            )
            schema = Schema(columns)
        names: Optional[List[str]] = None
        if not header:
            assert_or_throw(
                columns is not None,
                ValueError("columns must be set when csv has no header"),
            )
            names = schema.names if schema is not None else list(columns)
            read_opts.column_names = names
        if schema is not None:
            # parse straight into the requested types
            convert_opts.column_types = {
                f.name: f.type for f in schema.fields
                if not pa.types.is_nested(f.type)
            }
        elif not infer:
            # inference disabled: keep raw text (declare every column string)
            if names is None:
                import csv as _csv

                with open(path, "r", newline="") as fp:
                    names = next(_csv.reader(fp))
            convert_opts.column_types = {n: pa.string() for n in names}
        table = pacsv.read_csv(path, read_options=read_opts,
                               convert_options=convert_opts)
        if cols is not None:
            table = table.select(cols)
        return table
    if fmt == "json":
        table = pajson.read_json(path)
        if cols is not None:
            table = table.select(cols)
        return table
    raise NotImplementedError(fmt)


def save_df(
    df: DataFrame,
    path: str,
    format_hint: Optional[str] = None,
    mode: str = "overwrite",
    force_single: bool = False,
    partition_cols: Optional[List[str]] = None,
    **kwargs: Any,
) -> None:
    fmt = infer_format(path, format_hint)
    assert_or_throw(
        mode in ("overwrite", "append", "error"),
        NotImplementedError(f"invalid mode {mode}"),
    )
    if os.path.exists(path):
        if mode == "error":
            raise FileExistsError(path)
        if mode == "overwrite":
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.remove(path)
    if partition_cols:
        # hive-style partitioned dataset (reference native engine:
        # partition_spec.partition_by -> pandas to_parquet partition_cols)
        assert_or_throw(
            fmt == "parquet",
            NotImplementedError(f"partitioned save not supported for {fmt}"),
        )
        table_p = df.as_local_bounded().as_arrow(type_safe=True)
        pq.write_to_dataset(
            table_p, root_path=path, partition_cols=list(partition_cols),
            **kwargs,
        )
        return
    table = df.as_local_bounded().as_arrow(type_safe=True)
    if mode == "append" and os.path.exists(path):
        if os.path.isdir(path):
            target = os.path.join(path, f"part-{len(os.listdir(path))}.{fmt}")
            _save_single(table, target, fmt, kwargs)
            return
        # read the existing file with the SAME header convention we write
        # (csv is saved headerless by default), then align types to the new data
        load_kw: Dict[str, Any] = {}
        load_cols: Any = None
        if fmt == "csv":
            load_kw["header"] = bool(kwargs.get("header", False))
            if not load_kw["header"]:
                load_cols = list(table.schema.names)
        old = _load_single(path, fmt, load_cols, load_kw)
        if old.schema != table.schema:
            from fugue_tpu.dataframe.arrow_utils import cast_table
            from fugue_tpu.schema import Schema as _Schema

            old = cast_table(old.select(table.schema.names), _Schema(table.schema))
        table = pa.concat_tables([old, table])
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    _save_single(table, path, fmt, kwargs)


def _save_single(table: pa.Table, path: str, fmt: str, kwargs: Dict[str, Any]) -> None:
    if fmt == "parquet":
        pq.write_table(table, path, **kwargs)
        return
    if fmt == "csv":
        header = bool(kwargs.pop("header", False))
        opts = pacsv.WriteOptions(include_header=header)
        pacsv.write_csv(table, path, opts)
        return
    if fmt == "json":
        # line-delimited json (the cross-engine convention)
        import json as _json

        from fugue_tpu.dataframe.arrow_utils import table_to_rows

        names = table.schema.names
        with open(path, "w") as fp:
            for row in table_to_rows(table):
                fp.write(_json.dumps(dict(zip(names, row)), default=str) + "\n")
        return
    raise NotImplementedError(fmt)
