"""Host IO: parquet/csv/json load & save over the virtual filesystem
layer (reference fugue/_utils/io.py rebuilt on pyarrow + fugue_tpu.fs —
URI paths like ``memory://`` / ``gs://`` work everywhere a local path
does).

Files may be single files or directories of part files (the distributed
convention); saving with ``force_single`` writes one file (atomically —
a concurrent reader never observes a torn file), otherwise engines may
write a directory. Parquet directory reads go through pyarrow's dataset
machinery on a ``pyarrow.fs`` view of the URI's backend, so flat part
dirs AND hive-partitioned layouts load from any filesystem."""

import io as _stdio
from typing import Any, Dict, List, Optional, Union

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as pq

from fugue_tpu.dataframe import ArrowDataFrame, DataFrame, LocalBoundedDataFrame
from fugue_tpu.fs import FileSystemRegistry, make_default_registry
from fugue_tpu.lake.format import is_lake_uri
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw

_FORMATS = {".parquet": "parquet", ".csv": "csv", ".json": "json"}

_DEFAULT_FS: List[Optional[FileSystemRegistry]] = [None]


def default_fs() -> FileSystemRegistry:
    """Process-default registry used when no engine fs is supplied."""
    if _DEFAULT_FS[0] is None:
        _DEFAULT_FS[0] = make_default_registry()
    return _DEFAULT_FS[0]


def spec_partition_cols(
    partition_spec: Any, force_single: bool
) -> Optional[List[str]]:
    """The engine-shared save rule: a partition spec's keys become hive
    partition columns unless a single file was forced."""
    if partition_spec is None or force_single:
        return None
    by = list(partition_spec.partition_by)
    return by if len(by) > 0 else None


def infer_format(path: str, format_hint: Optional[str] = None) -> str:
    if format_hint is not None:
        assert_or_throw(
            format_hint in ("parquet", "csv", "json"),
            NotImplementedError(f"invalid format {format_hint}"),
        )
        return format_hint
    for suffix, fmt in _FORMATS.items():
        if path.endswith(suffix):
            return fmt
    raise NotImplementedError(f"can't infer format of {path}")


def _part_files(fs: FileSystemRegistry, path: str, fmt: str) -> List[str]:
    if fs.isdir(path):
        files = sorted(
            fs.join(path, f)
            for f in fs.listdir(path)
            if not f.startswith(".") and not f.startswith("_")
        )
        assert_or_throw(len(files) > 0, FileNotFoundError(f"no part files in {path}"))
        return files
    assert_or_throw(fs.exists(path), FileNotFoundError(path))
    return [path]


def load_df(
    path: Union[str, List[str]],
    format_hint: Optional[str] = None,
    columns: Any = None,
    fs: Optional[FileSystemRegistry] = None,
    **kwargs: Any,
) -> LocalBoundedDataFrame:
    fs = fs or default_fs()
    paths = [path] if isinstance(path, str) else list(path)
    if is_lake_uri(paths[0]):
        return _load_lake(paths, columns, fs, kwargs)
    fmt = infer_format(paths[0], format_hint)
    tables = []
    for p in paths:
        if fmt == "parquet" and fs.isdir(p):
            # dataset read: flat part dirs AND hive-partitioned layouts
            # (partition columns are restored from the directory names)
            cols = columns if isinstance(columns, list) else None
            pa_fs, local_path = fs.pyarrow_fs(p)
            t = pq.read_table(
                local_path, columns=cols, filesystem=pa_fs, **kwargs
            )
            # hive partition keys arrive dictionary-encoded; decode to
            # plain types (our schema language has no dictionary type)
            for i, f in enumerate(t.schema):
                if pa.types.is_dictionary(f.type):
                    t = t.set_column(
                        i, f.name, t.column(i).cast(f.type.value_type)
                    )
            tables.append(t)
            continue
        for f in _part_files(fs, p, fmt):
            # copy kwargs: the csv branch pops options, every file must see them
            tables.append(_load_single(fs, f, fmt, columns, dict(kwargs)))
    table = tables[0] if len(tables) == 1 else pa.concat_tables(tables)
    if isinstance(columns, str):  # schema expression: select + cast
        schema = Schema(columns)
        from fugue_tpu.dataframe.arrow_utils import cast_table

        table = cast_table(table.select(schema.names), schema)
        return ArrowDataFrame(table, schema)
    return ArrowDataFrame(table)


def _load_lake(
    paths: List[str], columns: Any, fs: FileSystemRegistry,
    kwargs: Dict[str, Any],
) -> LocalBoundedDataFrame:
    """``lake://`` load: resolve the snapshot (URI query and/or
    version/timestamp kwargs — the SQL ``AS OF`` lands here), let the
    lake layer do schema-evolution resolution and manifest-stats file
    pruning, and come back as a normal arrow frame."""
    from fugue_tpu.lake import LakeTable, parse_lake_uri

    assert_or_throw(
        len(paths) == 1,
        NotImplementedError("multiple lake:// paths in one load"),
    )
    table_uri, params = parse_lake_uri(paths[0])
    version = kwargs.pop("version", params.get("version"))
    timestamp = kwargs.pop("timestamp", params.get("timestamp"))
    pruning = kwargs.pop("pruning", None)
    conf = kwargs.pop("conf", None)
    assert_or_throw(
        len(kwargs) == 0,
        NotImplementedError(f"lake load got unknown options {sorted(kwargs)}"),
    )
    cols = columns if isinstance(columns, list) else None
    if isinstance(columns, str):
        cols = Schema(columns).names
    table = LakeTable(table_uri, fs=fs, conf=conf).scan(
        columns=cols,
        version=None if version is None else int(version),
        timestamp=None if timestamp is None else float(timestamp),
        pruning=pruning,
    )
    if isinstance(columns, str):  # schema expression: select + cast
        schema = Schema(columns)
        from fugue_tpu.dataframe.arrow_utils import cast_table

        return ArrowDataFrame(cast_table(table, schema), schema)
    return ArrowDataFrame(table)


def _save_lake(
    df: DataFrame, path: str, mode: str, fs: FileSystemRegistry,
    kwargs: Dict[str, Any],
) -> None:
    """``lake://`` save: a transactional commit instead of file
    replacement — overwrite/append map to the table operations,
    ``error`` refuses only when the table already exists."""
    from fugue_tpu.lake import LakeTable, parse_lake_uri

    table_uri, params = parse_lake_uri(path)
    assert_or_throw(
        len(params) == 0,
        ValueError(f"can't write to a pinned lake snapshot: {path}"),
    )
    writer_id = kwargs.pop("writer_id", None)
    writer_batch = kwargs.pop("writer_batch", None)
    kwargs.pop("batch_rows", None)  # row-group knob: no-op for lake
    assert_or_throw(
        len(kwargs) == 0,
        NotImplementedError(f"lake save got unknown options {sorted(kwargs)}"),
    )
    table = df.as_local_bounded().as_arrow(type_safe=True)
    lt = LakeTable(table_uri, fs=fs)
    if mode == "error":
        assert_or_throw(not lt.exists(), FileExistsError(path))
        lt.append(table)
    elif mode == "append":
        lt.append(
            table,
            writer_id=writer_id,
            writer_batch=None if writer_batch is None else int(writer_batch),
        )
    else:
        lt.overwrite(table)


def _load_single(
    fs: FileSystemRegistry, path: str, fmt: str, columns: Any,
    kwargs: Dict[str, Any],
) -> pa.Table:
    cols = columns if isinstance(columns, list) else None
    if fmt == "parquet":
        pa_fs, local_path = fs.pyarrow_fs(path)
        return pq.read_table(
            local_path, columns=cols, filesystem=pa_fs, **kwargs
        )
    if fmt == "csv":
        header = bool(kwargs.pop("header", True))
        infer = bool(kwargs.pop("infer_schema", False))
        schema: Optional[Schema] = None
        read_opts = pacsv.ReadOptions()
        convert_opts = pacsv.ConvertOptions()
        if isinstance(columns, str):
            assert_or_throw(
                not infer,
                ValueError(
                    "can't set typed columns together with infer_schema=True"
                ),
            )
            schema = Schema(columns)
        names: Optional[List[str]] = None
        if not header:
            assert_or_throw(
                columns is not None,
                ValueError("columns must be set when csv has no header"),
            )
            names = schema.names if schema is not None else list(columns)
            read_opts.column_names = names
        if schema is not None:
            # parse straight into the requested types
            convert_opts.column_types = {
                f.name: f.type for f in schema.fields
                if not pa.types.is_nested(f.type)
            }
        elif not infer:
            # inference disabled: keep raw text (declare every column string)
            if names is None:
                import csv as _csv

                with fs.open_input_stream(path) as raw:
                    text = _stdio.TextIOWrapper(raw, newline="")
                    names = next(_csv.reader(text))
            convert_opts.column_types = {n: pa.string() for n in names}
        with fs.open_input_stream(path) as fp:
            table = pacsv.read_csv(fp, read_options=read_opts,
                                   convert_options=convert_opts)
        if cols is not None:
            table = table.select(cols)
        return table
    if fmt == "json":
        with fs.open_input_stream(path) as fp:
            table = pajson.read_json(fp)
        if cols is not None:
            table = table.select(cols)
        return table
    raise NotImplementedError(fmt)


def save_df(
    df: DataFrame,
    path: str,
    format_hint: Optional[str] = None,
    mode: str = "overwrite",
    force_single: bool = False,
    partition_cols: Optional[List[str]] = None,
    fs: Optional[FileSystemRegistry] = None,
    **kwargs: Any,
) -> None:
    fs = fs or default_fs()
    assert_or_throw(
        mode in ("overwrite", "append", "error"),
        NotImplementedError(f"invalid mode {mode}"),
    )
    if is_lake_uri(path):
        assert_or_throw(
            not partition_cols,
            NotImplementedError("partitioned save into a lake table"),
        )
        _save_lake(df, path, mode, fs, kwargs)
        return
    fmt = infer_format(path, format_hint)
    # row-group streaming knob (fugue.jax.io.batch_rows): bounded-memory
    # buffered writes — not a pyarrow kwarg, never forward it
    batch_rows = int(kwargs.pop("batch_rows", 0) or 0)
    if fs.exists(path):
        if mode == "error":
            raise FileExistsError(path)
        if mode == "overwrite" and (fs.isdir(path) or partition_cols):
            # only directories (and dir-dataset targets) need pre-delete;
            # a single-file target is REPLACED by the atomic write, so the
            # old artifact survives until the new one commits — a failed
            # write never destroys data or exposes a no-file window
            fs.rm(path, recursive=True)
    if partition_cols:
        # hive-style partitioned dataset (reference native engine:
        # partition_spec.partition_by -> pandas to_parquet partition_cols)
        assert_or_throw(
            fmt == "parquet",
            NotImplementedError(f"partitioned save not supported for {fmt}"),
        )
        table_p = df.as_local_bounded().as_arrow(type_safe=True)
        pa_fs, local_path = fs.pyarrow_fs(path)
        pq.write_to_dataset(
            table_p, root_path=local_path,
            partition_cols=list(partition_cols), filesystem=pa_fs,
            **kwargs,
        )
        return
    table = df.as_local_bounded().as_arrow(type_safe=True)
    if mode == "append" and fs.exists(path):
        if fs.isdir(path):
            target = fs.join(path, f"part-{len(fs.listdir(path))}.{fmt}")
            _save_single(fs, table, target, fmt, kwargs, batch_rows)
            return
        # read the existing file with the SAME header convention we write
        # (csv is saved headerless by default), then align types to the new data
        load_kw: Dict[str, Any] = {}
        load_cols: Any = None
        if fmt == "csv":
            load_kw["header"] = bool(kwargs.get("header", False))
            if not load_kw["header"]:
                load_cols = list(table.schema.names)
        old = _load_single(fs, path, fmt, load_cols, load_kw)
        if old.schema != table.schema:
            from fugue_tpu.dataframe.arrow_utils import cast_table
            from fugue_tpu.schema import Schema as _Schema

            old = cast_table(old.select(table.schema.names), _Schema(table.schema))
        table = pa.concat_tables([old, table])
    _save_single(fs, table, path, fmt, kwargs, batch_rows)


def _save_single(
    fs: FileSystemRegistry, table: pa.Table, path: str, fmt: str,
    kwargs: Dict[str, Any], batch_rows: int = 0,
) -> None:
    if fmt == "parquet":
        if batch_rows > 0:
            # buffered batch write: encode row groups of at most
            # batch_rows so encoder working set stays bounded and a
            # streamed reader gets overlappable row groups back
            def _write_batched(fp: Any) -> None:
                with pq.ParquetWriter(fp, table.schema, **kwargs) as w:
                    for batch in table.to_batches(max_chunksize=batch_rows):
                        w.write_batch(batch)

            fs.write_file_atomic(path, _write_batched)
            return
        fs.write_file_atomic(path, lambda fp: pq.write_table(table, fp, **kwargs))
        return
    if fmt == "csv":
        header = bool(kwargs.pop("header", False))
        opts = pacsv.WriteOptions(include_header=header)
        fs.write_file_atomic(path, lambda fp: pacsv.write_csv(table, fp, opts))
        return
    if fmt == "json":
        # line-delimited json (the cross-engine convention)
        import json as _json

        from fugue_tpu.dataframe.arrow_utils import table_to_rows

        names = table.schema.names

        def _write_json(fp: Any) -> None:
            text = _stdio.TextIOWrapper(fp, encoding="utf-8")
            for row in table_to_rows(table):
                text.write(_json.dumps(dict(zip(names, row)), default=str) + "\n")
            text.flush()
            text.detach()  # the caller owns/closes the binary stream

        fs.write_file_atomic(path, _write_json)
        return
    raise NotImplementedError(fmt)
