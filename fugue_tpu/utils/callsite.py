"""User-callsite attribution, shared by fault notes and analyzer
diagnostics.

A workflow DAG is built at one place (user code) and fails at another
(runner/engine internals, possibly minutes later). Both the fault layer
(error notes spliced into runtime failures) and the static analyzer
(diagnostics pointing at the line that DEFINED a bad task) need the same
primitive: "the last N user (non-framework) frames of the current stack".
Extracted from the exception-surgery module so neither consumer drags in
traceback-pruning machinery.
"""

import traceback
from typing import List, Optional


def package_dir(prefix: str) -> Optional[str]:
    """The on-disk directory of the package named by a hide prefix
    (``'fugue_tpu.'`` -> ``'/…/fugue_tpu/'``), or None if unimportable."""
    import importlib
    import os

    try:
        mod = importlib.import_module(prefix.rstrip("."))
        f = getattr(mod, "__file__", None)
        if f is None:
            return None
        return os.path.dirname(os.path.abspath(f)).replace("\\", "/") + "/"
    except Exception:
        return None


def extract_user_callsite(inject: int, hide_prefixes: List[str]) -> List[str]:
    """Capture the current stack's last ``inject`` user (non-framework)
    frames as display strings, for splicing into runtime errors and
    analyzer diagnostics."""
    if inject <= 0:
        return []
    # resolve each hidden package to its REAL directory — fragment
    # matching ("/fugue_tpu/" in path) would also hide user code that
    # merely lives under a same-named folder (tests/fugue_tpu/...)
    pkg_dirs = [d for d in (package_dir(p) for p in hide_prefixes if p) if d]
    frames: List[List[str]] = []  # each entry: [header, code?] of one frame
    for frame in reversed(traceback.extract_stack()[:-1]):
        fname = frame.filename.replace("\\", "/")
        if any(fname.startswith(d) for d in pkg_dirs):
            continue
        entry = [f'  File "{frame.filename}", line {frame.lineno}, in {frame.name}']
        if frame.line:
            entry.append(f"    {frame.line}")
        frames.append(entry)
        if len(frames) >= inject:
            break
    res: List[str] = []
    for entry in reversed(frames):  # reverse frame ORDER, keep header/code pairs
        res.extend(entry)
    return res
