import hashlib
import inspect
import json
import uuid
from typing import Any


def _normalize(obj: Any) -> Any:
    """Convert an arbitrary object into a deterministic, json-able structure
    used for task/extension identity hashing (the determinism backbone: tasks
    with identical specs must hash identically across processes/runs —
    behavior parity with reference fugue/workflow/_tasks.py:85-98)."""
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, dict):
        return {str(k): _normalize(v) for k, v in sorted(obj.items(), key=lambda x: str(x[0]))}
    if isinstance(obj, (list, tuple)):
        return [_normalize(x) for x in obj]
    if isinstance(obj, type):
        return f"type:{obj.__module__}.{obj.__qualname__}"
    if inspect.isfunction(obj) or inspect.isbuiltin(obj):
        try:
            src = inspect.getsource(obj)
        except (OSError, TypeError):
            src = obj.__qualname__
        return f"func:{obj.__module__}.{obj.__qualname__}:{src}"
    if hasattr(obj, "__uuid__"):
        return f"uuid:{obj.__uuid__()}"
    return f"repr:{type(obj).__module__}.{type(obj).__qualname__}:{obj!r}"


def to_uuid(*args: Any) -> str:
    """Deterministic uuid string from arbitrary objects."""
    m = hashlib.md5()
    for a in args:
        m.update(json.dumps(_normalize(a), sort_keys=True, default=str).encode())
    return str(uuid.UUID(m.hexdigest()))
