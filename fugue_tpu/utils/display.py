"""Plain-text tabular rendering for Dataset.show() (PrettyTable replacement)."""

from typing import Any, List, Optional


def _cell(v: Any, max_width: int = 30) -> str:
    s = "NULL" if v is None else str(v)
    if len(s) > max_width:
        s = s[: max_width - 3] + "..."
    return s


def build_show_text(
    rows: List[List[Any]],
    schema: Any,
    title: Optional[str] = None,
    count: Optional[int] = None,
    truncated: bool = False,
) -> str:
    headers = [f"{f.name}:{_type_name(f.type)}" for f in schema.fields]
    str_rows = [[_cell(v) for v in row] for row in rows]
    widths = [len(h) for h in headers]
    for r in str_rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    lines = []
    if title:
        lines.append(title)
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    lines.append(sep)
    lines.append("|" + "|".join(f" {h.ljust(w)} " for h, w in zip(headers, widths)) + "|")
    lines.append(sep)
    for r in str_rows:
        lines.append("|" + "|".join(f" {c.ljust(w)} " for c, w in zip(r, widths)) + "|")
    lines.append(sep)
    if truncated:
        lines.append("(showing first rows only)")
    if count is not None:
        lines.append(f"Total count: {count}")
    return "\n".join(lines)


def _type_name(tp: Any) -> str:
    from fugue_tpu.schema import type_to_expr

    try:
        return type_to_expr(tp)
    except Exception:
        return str(tp)
