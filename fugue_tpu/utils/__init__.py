from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.params import ParamDict
from fugue_tpu.utils.lock import SerializableRLock
