"""One-pass streaming dataframe over an iterable of rows (reference
iterable_dataframe.py:16). Reading consumes the stream — ``peek_array`` uses
one-item lookahead."""

from typing import Any, Dict, Iterable, Iterator, List, Optional

from fugue_tpu.dataframe.array_dataframe import ArrayDataFrame
from fugue_tpu.dataframe.arrow_utils import cast_table, rows_to_table, table_to_rows
from fugue_tpu.dataframe.dataframe import (
    DataFrame,
    LocalBoundedDataFrame,
    LocalUnboundedDataFrame,
)
from fugue_tpu.utils.assertion import assert_or_throw


class _Peekable:
    def __init__(self, it: Iterator[Any]):
        self._it = it
        self._buffer: List[Any] = []

    def peek(self) -> Any:
        if not self._buffer:
            self._buffer.append(next(self._it))
        return self._buffer[0]

    def __iter__(self) -> Iterator[Any]:
        while True:
            if self._buffer:
                yield self._buffer.pop(0)
            else:
                try:
                    yield next(self._it)
                except StopIteration:
                    return


class IterableDataFrame(LocalUnboundedDataFrame):
    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            super().__init__(schema)
            self._native = _Peekable(iter([]))
        elif isinstance(df, IterableDataFrame):
            super().__init__(schema if schema is not None else df.schema)
            if schema is not None and schema != df.schema:
                idx = [df.schema.index_of_key(n) for n in self.schema.names]
                self._native = _Peekable(
                    [r[i] for i in idx] for r in df._native  # type: ignore
                )
            else:
                self._native = df._native
        elif isinstance(df, DataFrame):
            super().__init__(schema if schema is not None else df.schema)
            self._native = _Peekable(
                iter(df.as_array_iterable(self.schema.names, type_safe=False))
            )
        elif isinstance(df, Iterable):
            super().__init__(schema)
            self._native = _Peekable(iter(df))
        else:
            raise ValueError(f"can't initialize IterableDataFrame with {type(df)}")

    @property
    def native(self) -> Iterable[Any]:
        return self._native

    @property
    def empty(self) -> bool:
        try:
            self._native.peek()
            return False
        except StopIteration:
            return True

    def peek_array(self) -> List[Any]:
        try:
            return list(self._native.peek())
        except StopIteration:
            raise ValueError("dataframe is empty")

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        return IterableDataFrame(self, self.schema.exclude(cols))

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        return IterableDataFrame(self, self.schema.extract(cols))

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        res = IterableDataFrame(self)
        res._schema = self._rename_schema(columns)
        return res

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self._alter_schema(columns)
        if new_schema == self.schema:
            return self

        def gen() -> Iterator[List[Any]]:
            # stream in chunks through arrow casting
            chunk: List[Any] = []
            for row in self._native:
                chunk.append(row)
                if len(chunk) >= 10000:
                    yield from table_to_rows(
                        cast_table(rows_to_table(chunk, self.schema), new_schema)
                    )
                    chunk = []
            if chunk:
                yield from table_to_rows(
                    cast_table(rows_to_table(chunk, self.schema), new_schema)
                )

        return IterableDataFrame(gen(), new_schema)

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return list(self.as_array_iterable(columns, type_safe))

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        if not type_safe:
            if columns is None:
                yield from self._native
            else:
                idx = [self.schema.index_of_key(n) for n in columns]
                for row in self._native:
                    yield [row[i] for i in idx]
        else:
            # chunked type-safe conversion to stay streaming
            schema = self.schema
            chunk: List[Any] = []
            for row in self._native:
                chunk.append(row)
                if len(chunk) >= 10000:
                    yield from table_to_rows(rows_to_table(chunk, schema), columns)
                    chunk = []
            if chunk:
                yield from table_to_rows(rows_to_table(chunk, schema), columns)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        schema = self.schema if columns is None else self.schema.extract(columns)
        rows = []
        it = iter(self.as_array_iterable(columns, type_safe=True))
        for _ in range(n):
            try:
                rows.append(next(it))
            except StopIteration:
                break
        return ArrayDataFrame(rows, schema)
