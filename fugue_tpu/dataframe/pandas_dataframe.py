"""Pandas-backed dataframe (reference pandas_dataframe.py:31)."""

from typing import Any, Dict, Iterable, List, Optional

import pandas as pd

from fugue_tpu.dataframe.arrow_utils import (
    cast_table,
    normalize_dataframe_schema,
    pandas_to_table,
    table_to_pandas,
    table_to_rows,
)
from fugue_tpu.dataframe.dataframe import DataFrame, LocalBoundedDataFrame
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class PandasDataFrame(LocalBoundedDataFrame):
    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            super().__init__(schema)
            self._native = self.schema.create_empty_pandas()
        elif isinstance(df, pd.DataFrame):
            if schema is None:
                super().__init__(normalize_dataframe_schema(df))
                self._native = df.reset_index(drop=True)
            else:
                schema = Schema(schema)
                assert_or_throw(
                    set(schema.names) == set(df.columns),
                    ValueError(f"schema {schema} doesn't match columns {list(df.columns)}"),
                )
                pdf = df[schema.names].reset_index(drop=True)
                super().__init__(schema)
                self._native = self._coerce(pdf, schema)
        elif isinstance(df, pd.Series):
            raise ValueError("can't initialize PandasDataFrame with a Series")
        elif isinstance(df, DataFrame):
            super().__init__(schema if schema is not None else df.schema)
            self._native = df[self.schema.names].as_pandas() if schema is not None \
                else df.as_pandas()
        elif isinstance(df, Iterable):
            super().__init__(schema)
            from fugue_tpu.dataframe.arrow_utils import rows_to_table

            self._native = table_to_pandas(rows_to_table(df, self.schema))
        else:
            raise ValueError(f"can't initialize PandasDataFrame with {type(df)}")

    def _coerce(self, pdf: pd.DataFrame, schema: Schema) -> pd.DataFrame:
        """Align pandas dtypes with the target schema (via arrow round trip
        only when needed)."""
        try:
            inferred = normalize_dataframe_schema(pdf)
        except Exception:
            inferred = None
        if inferred is not None and inferred == schema:
            return pdf
        return table_to_pandas(pandas_to_table(pdf, schema))

    @property
    def native(self) -> pd.DataFrame:
        return self._native

    @property
    def empty(self) -> bool:
        return len(self._native) == 0

    def count(self) -> int:
        return len(self._native)

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        head = pandas_to_table(self._native.head(1), self.schema)
        return next(iter(table_to_rows(head)))

    @staticmethod
    def _wrap(pdf: pd.DataFrame, schema: Schema) -> "PandasDataFrame":
        """Build without re-coercion when dtypes are known-correct."""
        res = PandasDataFrame.__new__(PandasDataFrame)
        LocalBoundedDataFrame.__init__(res, schema)
        res._native = pdf
        return res

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.exclude(cols)
        return self._wrap(self._native[schema.names], schema)

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        schema = self.schema.extract(cols)
        return self._wrap(self._native[schema.names], schema)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        schema = self._rename_schema(columns)
        return self._wrap(self._native.rename(columns=columns), schema)

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self._alter_schema(columns)
        if new_schema == self.schema:
            return self
        table = cast_table(pandas_to_table(self._native, self.schema), new_schema)
        return PandasDataFrame(table_to_pandas(table), new_schema)

    def as_arrow(self, type_safe: bool = False) -> Any:
        return pandas_to_table(self._native, self.schema)

    def as_pandas(self) -> pd.DataFrame:
        return self._native

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return list(self.as_array_iterable(columns, type_safe))

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        if self.empty:
            return
        yield from table_to_rows(self.as_arrow(), columns)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        pdf = self._native if columns is None else self._native[columns]
        schema = self.schema if columns is None else self.schema.extract(columns)
        return PandasDataFrame(pdf.head(n), schema)
