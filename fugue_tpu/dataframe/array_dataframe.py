"""Row-major in-memory dataframe (reference array_dataframe.py:14)."""

from typing import Any, Dict, Iterable, List, Optional

from fugue_tpu.dataframe.arrow_utils import cast_table, rows_to_table, table_to_rows
from fugue_tpu.dataframe.dataframe import DataFrame, LocalBoundedDataFrame
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class ArrayDataFrame(LocalBoundedDataFrame):
    """DataFrame on a list of rows (each row a list). The cheapest frame to
    build; conversions are type-unsafe unless requested."""

    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            super().__init__(schema)
            self._native: List[Any] = []
        elif isinstance(df, DataFrame):
            super().__init__(schema if schema is not None else df.schema)
            if schema is None:
                self._native = df.as_array(type_safe=False)
            else:
                self._native = df.as_array(self.schema.names, type_safe=False)
        elif isinstance(df, Iterable):
            super().__init__(schema)
            self._native = [list(r) for r in df]
        else:
            raise ValueError(f"can't initialize ArrayDataFrame with {type(df)}")

    @property
    def native(self) -> List[Any]:
        return self._native

    @property
    def empty(self) -> bool:
        return len(self._native) == 0

    def count(self) -> int:
        return len(self._native)

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return list(self._native[0])

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.exclude(cols)
        return self._select_by_schema(schema)

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        schema = self.schema.extract(cols)
        return self._select_by_schema(schema)

    def _select_by_schema(self, schema: Schema) -> "ArrayDataFrame":
        idx = [self.schema.index_of_key(n) for n in schema.names]
        return ArrayDataFrame([[row[i] for i in idx] for row in self._native], schema)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        return ArrayDataFrame(self._native, self._rename_schema(columns))

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self._alter_schema(columns)
        if new_schema == self.schema:
            return self
        table = cast_table(rows_to_table(self._native, self.schema), new_schema)
        return ArrayDataFrame(list(table_to_rows(table)), new_schema)

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return list(self.as_array_iterable(columns, type_safe))

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        if not type_safe:
            if columns is None:
                yield from self._native
            else:
                idx = [self.schema.index_of_key(n) for n in columns]
                for row in self._native:
                    yield [row[i] for i in idx]
        else:
            table = rows_to_table(self._native, self.schema)
            yield from table_to_rows(table, columns)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        schema = self.schema if columns is None else self.schema.extract(columns)
        return ArrayDataFrame(
            list(self.as_array_iterable(columns, type_safe=False))[:n], schema
        )
