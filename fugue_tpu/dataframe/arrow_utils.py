"""Host-boundary columnar conversions (pyarrow <-> rows <-> pandas).

All dataframe implementations funnel their type-safe conversions through this
module so null/temporal/nested semantics are identical everywhere (the role
pyarrow+triad conversions play in the reference data layer, §2.1 of SURVEY).
Convention: ``as_array`` produces python-native values (datetime, date,
Decimal, bytes, dict for maps, dict for structs, list for lists); ``None`` is
the universal null (NaN/NaT normalize to None on the way in).
"""

from typing import Any, Iterable, Iterator, List, Optional

import pandas as pd
import pyarrow as pa

from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


def _normalize_cell(value: Any, tp: pa.DataType) -> Any:
    if value is None:
        return None
    if pa.types.is_map(tp):
        # pyarrow yields list of (k, v) tuples; we expose dicts
        if isinstance(value, list):
            return dict(value)
        return value
    if pa.types.is_list(tp) or pa.types.is_large_list(tp):
        return [_normalize_cell(v, tp.value_type) for v in value]
    if pa.types.is_struct(tp):
        return {
            f.name: _normalize_cell(value.get(f.name), f.type) for f in tp
        }
    if pa.types.is_timestamp(tp) and isinstance(value, pd.Timestamp):
        return value.to_pydatetime()
    return value


def _needs_normalize(tp: pa.DataType) -> bool:
    return (
        pa.types.is_map(tp)
        or pa.types.is_list(tp)
        or pa.types.is_large_list(tp)
        or pa.types.is_struct(tp)
        or pa.types.is_timestamp(tp)
    )


def table_to_rows(
    table: pa.Table, columns: Optional[List[str]] = None
) -> Iterator[List[Any]]:
    """Yield rows (as lists of python-native values) from an arrow table."""
    if columns is not None:
        table = table.select(columns)
    cols = [c.to_pylist() for c in table.columns]
    norm = [
        (_normalize_cell if _needs_normalize(f.type) else None, f.type)
        for f in table.schema
    ]
    for row in zip(*cols) if cols else iter([]):
        yield [
            fn(v, tp) if fn is not None else v
            for v, (fn, tp) in zip(row, norm)
        ]


def _prep_map_values(values: Iterable[Any], tp: pa.DataType) -> List[Any]:
    out = []
    for v in values:
        if isinstance(v, dict):
            v = list(v.items())
        out.append(v)
    return out


def rows_to_table(rows: Iterable[Any], schema: Schema) -> pa.Table:
    """Build an arrow table from row-major data (lists/tuples/dicts)."""
    cols: List[List[Any]] = [[] for _ in range(len(schema))]
    names = schema.names
    for row in rows:
        if isinstance(row, dict):
            for i, n in enumerate(names):
                cols[i].append(row.get(n))
        else:
            assert_or_throw(
                len(row) == len(names),
                ValueError(f"row width {len(row)} != schema width {len(names)}"),
            )
            for i, v in enumerate(row):
                cols[i].append(v)
    return cols_to_table(cols, schema)


def cols_to_table(cols: List[List[Any]], schema: Schema) -> pa.Table:
    arrays = []
    for values, field in zip(cols, schema.fields):
        if pa.types.is_map(field.type):
            values = _prep_map_values(values, field.type)
        try:
            arrays.append(pa.array(values, type=field.type, from_pandas=True))
        except (pa.ArrowTypeError, pa.ArrowInvalid):
            # e.g. ISO strings into date/timestamp columns: infer then cast
            inferred = pa.array(values, from_pandas=True)
            arrays.append(inferred.cast(field.type, safe=False))
    return pa.Table.from_arrays(arrays, schema=schema.pa_schema)


def pandas_to_table(df: pd.DataFrame, schema: Optional[Schema] = None) -> pa.Table:
    if schema is None:
        table = pa.Table.from_pandas(
            df, preserve_index=False, safe=False
        )
        # normalize large_string etc through Schema
        target = Schema(table.schema)
        if pa.schema(target.fields) != table.schema:
            table = table.cast(target.pa_schema)
        return table
    return pa.Table.from_pandas(
        df, schema=schema.pa_schema, preserve_index=False, safe=False
    )


def table_to_pandas(table: pa.Table) -> pd.DataFrame:
    return table.to_pandas(
        ignore_metadata=True,
        types_mapper=None,
        date_as_object=False,
    )


def normalize_dataframe_schema(df: pd.DataFrame) -> Schema:
    """Infer a Schema from a pandas dataframe; empty object columns become str."""
    fields = []
    for name in df.columns:
        assert_or_throw(isinstance(name, str), ValueError(f"column name {name!r} must be str"))
        s = df[name]
        if s.dtype == object and (len(s) == 0 or s.isna().all()):
            fields.append(pa.field(name, pa.string()))
        else:
            fields.append(pa.field(name, pa.Array.from_pandas(s).type))
    return Schema(fields)


def cast_table(table: pa.Table, schema: Schema) -> pa.Table:
    """Cast a table to a new schema (same width; names taken from ``schema``)."""
    assert_or_throw(
        table.num_columns == len(schema),
        ValueError("column count mismatch in cast"),
    )
    arrays = []
    for col, field in zip(table.columns, schema.fields):
        combined = col.combine_chunks() if col.num_chunks != 1 else col.chunk(0)
        if combined.type == field.type:
            arrays.append(combined)
        elif pa.types.is_string(field.type) and pa.types.is_timestamp(combined.type):
            # seconds precision like python str(datetime) — arrow's
            # native cast appends ".000000" (reference renders
            # "2020-01-01 03:04:05", fugue_test/dataframe_suite.py:372)
            vals = [
                None if v is None else str(v)
                for v in combined.to_pylist()
            ]
            arrays.append(pa.array(vals, type=pa.string()))
        elif pa.types.is_string(field.type) and pa.types.is_boolean(combined.type):
            # match python str(bool) casing: True/False
            vals = [None if v is None else str(v) for v in combined.to_pylist()]
            arrays.append(pa.array(vals, type=pa.string()))
        elif pa.types.is_boolean(field.type) and pa.types.is_string(combined.type):
            def _to_b(v: Any) -> Any:
                if v is None:
                    return None
                lv = v.strip().lower()
                if lv in ("true", "1"):
                    return True
                if lv in ("false", "0"):
                    return False
                raise ValueError(f"can't cast {v!r} to bool")
            arrays.append(
                pa.array([_to_b(v) for v in combined.to_pylist()], type=pa.bool_())
            )
        else:
            arrays.append(combined.cast(field.type, safe=False))
    return pa.Table.from_arrays(arrays, schema=schema.pa_schema)
