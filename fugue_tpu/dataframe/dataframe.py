"""DataFrame abstraction: schema-carrying, conversion-rich dataframes.

Parity target: reference ``fugue/dataframe/dataframe.py:29`` (DataFrame,
LocalDataFrame, LocalBoundedDataFrame, YieldedDataFrame) — rebuilt from
scratch with lazy schema resolution and arrow-funnelled conversions.
"""

from abc import abstractmethod
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

import pandas as pd
import pyarrow as pa

from fugue_tpu.collections.yielded import Yielded
from fugue_tpu.dataset.dataset import Dataset, DatasetDisplay, get_dataset_display
from fugue_tpu.plugins import fugue_plugin
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw
from fugue_tpu.utils.display import build_show_text
from fugue_tpu.utils.lock import SerializableRLock


class DataFrame(Dataset):
    """Abstract schema-carrying dataframe. ``schema`` may be provided lazily
    as a callable — resolution is locked and happens at most once (mirrors the
    lazy-schema design at reference dataframe.py:52, needed so expensive
    backends don't compute schemas for frames that are never inspected)."""

    def __init__(self, schema: Any = None):
        super().__init__()
        if callable(schema):
            self._schema: Union[Schema, Callable[[], Any]] = schema
            self._schema_discovered = False
        else:
            self._schema = Schema(schema)
            self._schema.assert_not_empty()
            self._schema_discovered = True
        self._lazy_schema_lock = SerializableRLock()

    @property
    def schema(self) -> Schema:
        if self._schema_discovered:
            return self._schema  # type: ignore
        with self._lazy_schema_lock:
            if not self._schema_discovered:
                schema = self._schema()  # type: ignore
                self._schema = schema if isinstance(schema, Schema) else Schema(schema)
                self._schema.assert_not_empty()
                self._schema_discovered = True
        return self._schema  # type: ignore

    @property
    def schema_discovered(self) -> bool:
        return self._schema_discovered

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    # ---- abstract interface ---------------------------------------------
    @abstractmethod
    def peek_array(self) -> List[Any]:  # pragma: no cover - interface
        """First row as a list; raises when empty."""
        raise NotImplementedError

    @abstractmethod
    def as_local_bounded(self) -> "LocalBoundedDataFrame":  # pragma: no cover
        raise NotImplementedError

    @abstractmethod
    def _drop_cols(self, cols: List[str]) -> "DataFrame":  # pragma: no cover
        raise NotImplementedError

    @abstractmethod
    def rename(self, columns: Dict[str, str]) -> "DataFrame":  # pragma: no cover
        raise NotImplementedError

    @abstractmethod
    def alter_columns(self, columns: Any) -> "DataFrame":  # pragma: no cover
        """Cast a subset of columns to new types (no reorder/drop)."""
        raise NotImplementedError

    @abstractmethod
    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:  # pragma: no cover - interface
        raise NotImplementedError

    @abstractmethod
    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:  # pragma: no cover - interface
        raise NotImplementedError

    @abstractmethod
    def _select_cols(self, cols: List[Any]) -> "DataFrame":  # pragma: no cover
        raise NotImplementedError

    @abstractmethod
    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> "LocalBoundedDataFrame":  # pragma: no cover - interface
        raise NotImplementedError

    # ---- derived conversions --------------------------------------------
    def peek_dict(self) -> Dict[str, Any]:
        arr = self.peek_array()
        return dict(zip(self.schema.names, arr))

    def as_local(self) -> "LocalDataFrame":
        return self.as_local_bounded()

    def as_pandas(self) -> pd.DataFrame:
        from fugue_tpu.dataframe.arrow_utils import table_to_pandas

        return table_to_pandas(self.as_arrow())

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        from fugue_tpu.dataframe.arrow_utils import rows_to_table

        return rows_to_table(self.as_array_iterable(type_safe=True), self.schema)

    def as_dict_iterable(
        self, columns: Optional[List[str]] = None
    ) -> Iterable[Dict[str, Any]]:
        names = self.schema.names if columns is None else columns
        for row in self.as_array_iterable(columns, type_safe=True):
            yield dict(zip(names, row))

    def as_dicts(self, columns: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        return list(self.as_dict_iterable(columns))

    def drop(self, columns: List[str]) -> "DataFrame":
        schema = self.schema.exclude(columns)  # validates names
        assert_or_throw(
            len(schema) > 0, ValueError("can't drop all columns")
        )
        assert_or_throw(
            len(set(columns)) == len(columns) and all(c in self.schema for c in columns),
            ValueError(f"invalid columns to drop {columns}"),
        )
        return self._drop_cols(list(columns))

    def __getitem__(self, columns: List[Any]) -> "DataFrame":
        assert_or_throw(
            isinstance(columns, list) and len(columns) > 0,
            ValueError("columns must be a non-empty list"),
        )
        assert_or_throw(
            all(c in self.schema for c in columns),
            KeyError(f"{columns} not all in {self.schema}"),
        )
        return self._select_cols(columns)

    def get_info_str(self) -> str:
        return f"{type(self).__name__}({self.schema})"

    def __repr__(self) -> str:
        return self.get_info_str()

    def _rename_schema(self, columns: Dict[str, str]) -> Schema:
        return self.schema.rename(columns)

    def _alter_schema(self, subschema: Any) -> Schema:
        new_schema = self.schema.alter(subschema)
        return new_schema


class LocalDataFrame(DataFrame):
    """A dataframe fully living in the driver process."""

    @property
    def is_local(self) -> bool:
        return True

    @property
    def num_partitions(self) -> int:
        return 1

    def as_local_bounded(self) -> "LocalBoundedDataFrame":
        if isinstance(self, LocalBoundedDataFrame):
            return self
        from fugue_tpu.dataframe.array_dataframe import ArrayDataFrame

        res = ArrayDataFrame(list(self.as_array_iterable(type_safe=True)), self.schema)
        if self.has_metadata:
            res.reset_metadata(self.metadata)
        return res


class LocalBoundedDataFrame(LocalDataFrame):
    @property
    def is_bounded(self) -> bool:
        return True


class LocalUnboundedDataFrame(LocalDataFrame):
    @property
    def is_bounded(self) -> bool:
        return False

    def count(self) -> int:
        raise ValueError("can't count an unbounded dataframe")


class YieldedDataFrame(Yielded):
    """Handle to a dataframe produced by another workflow run (reference
    dataframe.py:366)."""

    def __init__(self, yid: str):
        super().__init__(yid)
        self._df: Any = None

    @property
    def is_set(self) -> bool:
        return self._df is not None

    def set_value(self, df: DataFrame) -> None:
        self._df = df

    @property
    def result(self) -> DataFrame:
        assert_or_throw(self.is_set, ValueError("value is not set"))
        return self._df


class _DataFrameDisplay(DatasetDisplay):
    def show(
        self, n: int = 10, with_count: bool = False, title: Optional[str] = None
    ) -> None:
        df: DataFrame = self._ds  # type: ignore
        # fetch one extra row so "exactly n rows" isn't reported as truncated
        head_rows = df.head(n + 1).as_array(type_safe=True)
        print(
            build_show_text(
                head_rows[:n],
                df.schema,
                title=title or df.get_info_str(),
                count=df.count() if with_count and df.is_bounded else None,
                truncated=len(head_rows) > n,
            )
        )


@get_dataset_display.candidate(
    lambda ds: isinstance(ds, DataFrame), priority=0.5
)
def _get_dataframe_display(ds: DataFrame) -> DatasetDisplay:
    return _DataFrameDisplay(ds)


@fugue_plugin
def as_fugue_df(df: Any, **kwargs: Any) -> DataFrame:
    """Convert any supported object (pandas/arrow/list/DataFrame/...) into a
    fugue_tpu DataFrame; backends register candidates for their own types."""
    if isinstance(df, DataFrame):
        return df
    raise NotImplementedError(f"no conversion from {type(df)} to DataFrame")
