"""DataFrames: an ordered collection of named/unnamed DataFrames, the input
unit for cotransform and SQL (reference fugue/dataframe/dataframes.py)."""

from typing import Any, Dict

from fugue_tpu.dataframe.dataframe import DataFrame
from fugue_tpu.utils.assertion import assert_or_throw


class DataFrames(Dict[str, DataFrame]):
    """Either all-named (dict-like) or all-unnamed (positional, auto-keyed
    ``_0, _1, ...``); mixing the two raises."""

    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__()
        self._has_dict_name = False
        for a in args:
            self._add(a)
        for k, v in kwargs.items():
            self._append_named(k, v)

    def _add(self, obj: Any) -> None:
        if obj is None:
            return
        if isinstance(obj, DataFrames):
            if obj.has_dict:
                for k, v in obj.items():
                    self._append_named(k, v)
            else:
                for v in obj.values():
                    self._append_unnamed(v)
        elif isinstance(obj, dict):
            for k, v in obj.items():
                self._append_named(k, v)
        elif isinstance(obj, DataFrame):
            self._append_unnamed(obj)
        elif isinstance(obj, (list, tuple)):
            for x in obj:
                self._add(x)
        else:
            raise ValueError(f"{type(obj)} is not acceptable in DataFrames")

    def _check_df(self, name: str, df: Any) -> None:
        assert_or_throw(
            isinstance(df, DataFrame),
            ValueError(f"{name}: {type(df)} is not a DataFrame"),
        )
        assert_or_throw(name not in self, KeyError(f"duplicated name {name}"))

    def _append_named(self, name: str, df: Any) -> None:
        assert_or_throw(
            self._has_dict_name or len(self) == 0,
            ValueError("can't mix named and unnamed dataframes"),
        )
        self._check_df(name, df)
        self._has_dict_name = True
        super().__setitem__(name, df)

    def _append_unnamed(self, df: Any) -> None:
        assert_or_throw(
            not self._has_dict_name,
            ValueError("can't mix named and unnamed dataframes"),
        )
        name = f"_{len(self)}"
        self._check_df(name, df)
        super().__setitem__(name, df)

    @property
    def has_dict(self) -> bool:
        return self._has_dict_name

    def __setitem__(self, key: str, value: DataFrame) -> None:
        self._append_named(key, value)

    def update(self, *args: Any, **kwargs: Any) -> None:  # type: ignore[override]
        for k, v in dict(*args, **kwargs).items():
            self._append_named(k, v)

    def setdefault(self, key: str, default: Any = None) -> DataFrame:  # type: ignore[override]
        if key not in self:
            self._append_named(key, default)
        return self[key]

    def pop(self, *args: Any) -> DataFrame:  # type: ignore[override]
        raise NotImplementedError("DataFrames is append-only")

    def popitem(self) -> Any:
        raise NotImplementedError("DataFrames is append-only")

    def __delitem__(self, key: str) -> None:
        raise NotImplementedError("DataFrames is append-only")

    def __getitem__(self, key: Any) -> DataFrame:  # type: ignore[override]
        if isinstance(key, int):
            return list(self.values())[key]
        return super().__getitem__(key)

    def convert(self, func: Any) -> "DataFrames":
        res = DataFrames()
        for k, v in self.items():
            if self._has_dict_name:
                res._append_named(k, func(v))
            else:
                res._append_unnamed(func(v))
        return res
