"""Functional dataframe API: plugin dispatchers that work on ANY supported
dataframe-ish object (fugue_tpu DataFrames, pandas, arrow, row lists, and —
once registered — jax block frames). Parity: reference fugue/dataframe/api.py."""

from typing import Any, Dict, Iterable, List, Optional, Tuple

import pandas as pd
import pyarrow as pa

from fugue_tpu.dataset.api import (  # noqa: F401  (re-exported)
    as_fugue_dataset,
    count,
    is_bounded,
    is_empty,
    is_local,
    show,
)
from fugue_tpu.dataframe.array_dataframe import ArrayDataFrame
from fugue_tpu.dataframe.arrow_dataframe import ArrowDataFrame
from fugue_tpu.dataframe.dataframe import (
    DataFrame,
    LocalBoundedDataFrame,
    as_fugue_df,
)
from fugue_tpu.dataframe.pandas_dataframe import PandasDataFrame
from fugue_tpu.plugins import fugue_plugin
from fugue_tpu.schema import Schema


@fugue_plugin
def is_df(df: Any) -> bool:
    """Whether the object is recognized as a dataframe by any plugin."""
    return isinstance(df, (DataFrame, pd.DataFrame, pa.Table))


@as_fugue_df.candidate(lambda df, **kw: isinstance(df, pd.DataFrame))
def _pd_as_fugue_df(df: pd.DataFrame, schema: Any = None, **kwargs: Any) -> DataFrame:
    return PandasDataFrame(df, schema=schema)


@as_fugue_df.candidate(lambda df, **kw: isinstance(df, pa.Table))
def _pa_as_fugue_df(df: pa.Table, schema: Any = None, **kwargs: Any) -> DataFrame:
    return ArrowDataFrame(df, schema=schema)


@as_fugue_df.candidate(
    lambda df, **kw: isinstance(df, (list, tuple)) and "schema" in kw
)
def _rows_as_fugue_df(df: Any, schema: Any = None, **kwargs: Any) -> DataFrame:
    return ArrayDataFrame(df, schema=schema)


@fugue_plugin
def get_native_as_df(df: Any) -> Any:
    """Return the backend-native dataframe object."""
    if isinstance(df, DataFrame):
        return df.native
    if isinstance(df, (pd.DataFrame, pa.Table)):
        return df
    raise NotImplementedError(f"no native conversion for {type(df)}")


def get_schema(df: Any) -> Schema:
    return as_fugue_df(df).schema

def get_column_names(df: Any) -> List[Any]:
    return get_schema(df).names


def rename(df: Any, columns: Dict[str, Any], as_fugue: bool = False) -> Any:
    if len(columns) == 0:
        return df
    return _adjust(as_fugue_df(df).rename(columns), df, as_fugue)


def drop_columns(df: Any, columns: List[str], as_fugue: bool = False) -> Any:
    return _adjust(as_fugue_df(df).drop(columns), df, as_fugue)


def select_columns(df: Any, columns: List[Any], as_fugue: bool = False) -> Any:
    return _adjust(as_fugue_df(df)[columns], df, as_fugue)


def alter_columns(df: Any, columns: Any, as_fugue: bool = False) -> Any:
    return _adjust(as_fugue_df(df).alter_columns(columns), df, as_fugue)


def head(
    df: Any, n: int, columns: Optional[List[str]] = None, as_fugue: bool = False
) -> Any:
    return _adjust(as_fugue_df(df).head(n, columns), df, as_fugue)


def peek_array(df: Any) -> List[Any]:
    return as_fugue_df(df).peek_array()


def peek_dict(df: Any) -> Dict[str, Any]:
    return as_fugue_df(df).peek_dict()


def as_array(
    df: Any, columns: Optional[List[str]] = None, type_safe: bool = False
) -> List[Any]:
    return as_fugue_df(df).as_array(columns, type_safe)


def as_array_iterable(
    df: Any, columns: Optional[List[str]] = None, type_safe: bool = False
) -> Iterable[Any]:
    return as_fugue_df(df).as_array_iterable(columns, type_safe)


def as_dict_iterable(df: Any, columns: Optional[List[str]] = None) -> Iterable[Dict]:
    return as_fugue_df(df).as_dict_iterable(columns)


def as_pandas(df: Any) -> pd.DataFrame:
    if isinstance(df, pd.DataFrame):
        return df
    return as_fugue_df(df).as_pandas()


def as_arrow(df: Any) -> pa.Table:
    if isinstance(df, pa.Table):
        return df
    return as_fugue_df(df).as_arrow()


def normalize_dataframes(dfs: Any) -> Any:
    from fugue_tpu.dataframe.dataframes import DataFrames

    if isinstance(dfs, DataFrames):
        return dfs
    if isinstance(dfs, dict):
        return DataFrames({k: as_fugue_df(v) for k, v in dfs.items()})
    if isinstance(dfs, (list, tuple)):
        return DataFrames([as_fugue_df(v) for v in dfs])
    return DataFrames(as_fugue_df(dfs))


def _adjust(result: DataFrame, original: Any, as_fugue: bool) -> Any:
    """Return fugue_tpu DataFrame or downgrade to the original's native type."""
    if as_fugue or isinstance(original, DataFrame):
        return result
    if isinstance(original, pd.DataFrame):
        return result.as_pandas()
    if isinstance(original, pa.Table):
        return result.as_arrow()
    return result
