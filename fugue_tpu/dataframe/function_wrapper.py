"""The "interfaceless" core: map annotated python function signatures onto
dataframe conversions so plain functions become transformers/processors.

Parity target: reference ``fugue/dataframe/function_wrapper.py:41-463`` —
each parameter/return annotation resolves to a one-letter code; converters
validate the full code string with a regex (e.g. a transformer body must
match ``^[lpqrRmMdPQ][fF]?x*$``).

Codes:
  input/output dataframes --
    d DataFrame            l LocalDataFrame        p pd.DataFrame
    q pa.Table             r List[List[Any]]       R Iterable[List[Any]]
    m List[Dict[str,Any]]  M Iterable[Dict[str,Any]]
    P Iterable[pd.DataFrame]   Q Iterable[pa.Table]
    c DataFrames (multi-df)
  specials --
    f callable (required callback)   F Optional[callable]
    e ExecutionEngine                x other keyword params
    s PartitionCursor? (not used: cursor comes via context)
  output only --
    n None (output extensions)
"""

import inspect
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Union,
    get_args,
    get_origin,
    get_type_hints,
)

import pandas as pd
import pyarrow as pa

from fugue_tpu.dataframe import (
    ArrayDataFrame,
    ArrowDataFrame,
    DataFrame,
    DataFrames,
    IterableArrowDataFrame,
    IterableDataFrame,
    IterablePandasDataFrame,
    LocalDataFrame,
    LocalDataFrameIterableDataFrame,
    PandasDataFrame,
)
from fugue_tpu.exceptions import FugueInterfacelessError
from fugue_tpu.plugins import fugue_plugin
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class FunctionSignatureError(FugueInterfacelessError, TypeError):
    """A function's signature can't map onto the required extension shape
    (TypeError kept for pre-hierarchy callers)."""


class AnnotatedParam:
    """Handler for one annotation kind."""

    code = "x"
    format_hint: Optional[str] = None

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        raise NotImplementedError  # pragma: no cover

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        raise NotImplementedError  # pragma: no cover

    def count(self, obj: Any) -> int:
        """Row count of a produced output (for outputters' bookkeeping)."""
        return -1


_PARAM_REGISTRY: List[Any] = []  # (matcher, param_factory)


def fugue_annotated_param(
    annotation: Any, matcher: Optional[Callable[[Any], bool]] = None
) -> Callable:
    """Register an AnnotatedParam class for an annotation (the extension
    point backends use to accept their native frame types in transformers —
    the fugue_polars integration pattern, SURVEY §2.7)."""

    def deco(cls: type) -> type:
        if matcher is not None:
            _PARAM_REGISTRY.append((matcher, cls))
        else:
            _PARAM_REGISTRY.append((lambda a: a == annotation, cls))
        return cls

    return deco


def _resolve_param(annotation: Any) -> Optional[AnnotatedParam]:
    for matcher, cls in reversed(_PARAM_REGISTRY):
        try:
            if matcher(annotation):
                return cls()
        except Exception:
            continue
    return None


# ---- dataframe params ------------------------------------------------------
@fugue_annotated_param(DataFrame)
class _DataFrameParam(AnnotatedParam):
    code = "d"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        return df

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        assert_or_throw(
            isinstance(output, DataFrame), ValueError(f"{output} is not a DataFrame")
        )
        assert_or_throw(
            output.schema == schema,
            ValueError(f"schema mismatch {output.schema} vs {schema}"),
        )
        return output.as_local()


@fugue_annotated_param(LocalDataFrame)
class _LocalDataFrameParam(_DataFrameParam):
    code = "l"


@fugue_annotated_param(pd.DataFrame)
class _PandasParam(AnnotatedParam):
    code = "p"
    format_hint = "pandas"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        return df.as_pandas()

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        assert_or_throw(
            isinstance(output, pd.DataFrame), ValueError("output is not pd.DataFrame")
        )
        return PandasDataFrame(output, schema)

    def count(self, obj: Any) -> int:
        return len(obj)


@fugue_annotated_param(pa.Table)
class _ArrowParam(AnnotatedParam):
    code = "q"
    format_hint = "pyarrow"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        return df.as_arrow()

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        assert_or_throw(
            isinstance(output, pa.Table), ValueError("output is not pa.Table")
        )
        return ArrowDataFrame(output, schema)

    def count(self, obj: Any) -> int:
        return obj.num_rows


@fugue_annotated_param(List[List[Any]])
class _RowsParam(AnnotatedParam):
    code = "r"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        return df.as_array(type_safe=True)

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        return ArrayDataFrame(output, schema)

    def count(self, obj: Any) -> int:
        return len(obj)


@fugue_annotated_param(Iterable[List[Any]])
class _IterRowsParam(AnnotatedParam):
    code = "R"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        return df.as_array_iterable(type_safe=True)

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        return IterableDataFrame(output, schema)


@fugue_annotated_param(List[Dict[str, Any]])
class _DictsParam(AnnotatedParam):
    code = "m"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        return list(df.as_dict_iterable())

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        return ArrayDataFrame(
            ([row.get(n) for n in schema.names] for row in output), schema
        )

    def count(self, obj: Any) -> int:
        return len(obj)


@fugue_annotated_param(Iterable[Dict[str, Any]])
class _IterDictsParam(AnnotatedParam):
    code = "M"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        return df.as_dict_iterable()

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        return IterableDataFrame(
            ([row.get(n) for n in schema.names] for row in output), schema
        )


@fugue_annotated_param(Iterable[pd.DataFrame])
class _IterPandasParam(AnnotatedParam):
    code = "P"
    format_hint = "pandas"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        if isinstance(df, LocalDataFrameIterableDataFrame):
            return (chunk.as_pandas() for chunk in df.native)
        return iter([df.as_pandas()])

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        return IterablePandasDataFrame(
            (PandasDataFrame(o, schema) for o in output), schema
        )


@fugue_annotated_param(Iterable[pa.Table])
class _IterArrowParam(AnnotatedParam):
    code = "Q"
    format_hint = "pyarrow"

    def to_input(self, df: LocalDataFrame, ctx: Dict[str, Any]) -> Any:
        if isinstance(df, LocalDataFrameIterableDataFrame):
            return (chunk.as_arrow() for chunk in df.native)
        return iter([df.as_arrow()])

    def to_output_df(self, output: Any, schema: Schema, ctx: Dict[str, Any]) -> LocalDataFrame:
        return IterableArrowDataFrame(
            (ArrowDataFrame(o, schema) for o in output), schema
        )


@fugue_annotated_param(DataFrames)
class _DataFramesParam(AnnotatedParam):
    code = "c"


# Iterator[...] behaves like Iterable[...]
fugue_annotated_param(Iterator[List[Any]])(_IterRowsParam)
fugue_annotated_param(Iterator[Dict[str, Any]])(_IterDictsParam)
fugue_annotated_param(Iterator[pd.DataFrame])(_IterPandasParam)
fugue_annotated_param(Iterator[pa.Table])(_IterArrowParam)


# ---- special params --------------------------------------------------------
class _CallbackParam(AnnotatedParam):
    code = "f"


class _OptionalCallbackParam(AnnotatedParam):
    code = "F"


class _EngineParam(AnnotatedParam):
    code = "e"


class _OtherParam(AnnotatedParam):
    code = "x"


class _NoneParam(AnnotatedParam):
    code = "n"


_DF_INPUT_CODES = "dlpqrRmMPQj"
_DF_OUTPUT_CODES = "dlpqrRmMPQj"


def annotation_code(annotation: Any) -> str:
    p = _annotation_param(annotation)
    return p.code


def _annotation_param(anno: Any) -> AnnotatedParam:
    from fugue_tpu.execution.execution_engine import ExecutionEngine

    if anno is None or anno is type(None) or anno is inspect.Parameter.empty:
        return _OtherParam()
    if anno == "None":
        return _NoneParam()
    # Callable / Optional[Callable]
    import collections.abc as _abc

    origin = get_origin(anno)
    if anno is Callable or anno is callable or origin is _abc.Callable:
        return _CallbackParam()
    if origin is Union:
        args = [a for a in get_args(anno) if a is not type(None)]
        if len(args) == 1:
            inner = _annotation_param(args[0])
            if inner.code == "f":
                return _OptionalCallbackParam()
            return inner
    if isinstance(anno, type) and issubclass(anno, ExecutionEngine):
        return _EngineParam()
    resolved = _resolve_param(anno)
    if resolved is not None:
        return resolved
    # typing generics equality (List[List[Any]] etc.) handled by registry via ==
    return _OtherParam()


class _Param:
    def __init__(self, name: str, param: AnnotatedParam, required: bool):
        self.name = name
        self.param = param
        self.required = required

    @property
    def code(self) -> str:
        return self.param.code


class DataFrameFunctionWrapper:
    """Wrap a plain function: classify each param/return, validate the code
    string, and at call time convert dataframes to the annotated formats."""

    def __init__(self, func: Callable, params_re: str = ".*", return_re: str = ".*"):
        import re

        self._func = func
        sig = inspect.signature(func)
        try:
            hints = get_type_hints(func)
        except Exception:
            hints = {}
        self._params: List[_Param] = []
        for name, p in sig.parameters.items():
            assert_or_throw(
                p.kind
                not in (inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD),
                TypeError("*args/**kwargs not supported in fugue functions"),
            )
            anno = hints.get(name, p.annotation)
            self._params.append(
                _Param(name, _annotation_param(anno), p.default is inspect.Parameter.empty)
            )
        ret_anno = hints.get("return", sig.return_annotation)
        if ret_anno is None or ret_anno is type(None) or (
            ret_anno is inspect.Signature.empty
        ):
            self._rt: AnnotatedParam = _NoneParam()
        else:
            self._rt = _annotation_param(ret_anno)
            if isinstance(self._rt, _OtherParam):
                self._rt = _NoneParam()
        self._input_code = "".join(p.code for p in self._params)
        assert_or_throw(
            re.match(params_re, self._input_code) is not None,
            FunctionSignatureError(
                f"signature code {self._input_code!r} of {func} doesn't match "
                f"{params_re!r}"
            ),
        )
        assert_or_throw(
            re.match(return_re, self._rt.code) is not None,
            FunctionSignatureError(
                f"return code {self._rt.code!r} of {func} doesn't match "
                f"{return_re!r}"
            ),
        )

    @property
    def func(self) -> Callable:
        return self._func

    @property
    def input_code(self) -> str:
        return self._input_code

    @property
    def output_code(self) -> str:
        return self._rt.code

    @property
    def params(self) -> List[_Param]:
        return self._params

    @property
    def need_engine(self) -> bool:
        return "e" in self._input_code

    @property
    def need_callback(self) -> bool:
        return "f" in self._input_code or "F" in self._input_code

    def get_format_hint(self) -> Optional[str]:
        for p in self._params:
            if p.param.format_hint is not None:
                return p.param.format_hint
        return self._rt.format_hint

    def run(
        self,
        args: List[Any],
        kwargs: Dict[str, Any],
        output_schema: Any = None,
        output: bool = True,
        ctx: Optional[Dict[str, Any]] = None,
        ignore_unknown: bool = True,
    ) -> Any:
        """Call the wrapped function: ``args`` are LocalDataFrames (or
        DataFrames collection) mapped in order onto dataframe-coded params;
        ``kwargs`` fill the ``x`` params; callback/engine come from ``ctx``."""
        ctx = ctx or {}
        call_args: Dict[str, Any] = {}
        dfs = list(args)
        for p in self._params:
            if p.code in _DF_INPUT_CODES and len(dfs) > 0:
                call_args[p.name] = p.param.to_input(dfs.pop(0), ctx)
            elif p.code == "c":
                call_args[p.name] = dfs.pop(0)
            elif p.code in ("f", "F"):
                cb = ctx.get("callback")
                assert_or_throw(
                    cb is not None or p.code == "F",
                    ValueError(f"callback required by {p.name} but not provided"),
                )
                call_args[p.name] = cb
            elif p.code == "e":
                call_args[p.name] = ctx.get("engine")
            else:  # x
                if p.name in kwargs:
                    call_args[p.name] = kwargs[p.name]
                elif p.required:
                    raise ValueError(f"param {p.name} is required but not provided")
        if not ignore_unknown:
            known = {p.name for p in self._params}
            unknown = [k for k in kwargs if k not in known]
            assert_or_throw(
                len(unknown) == 0, ValueError(f"unknown params {unknown}")
            )
        res = self._func(**call_args)
        if not output:
            if isinstance(res, Iterator):
                for _ in res:  # drain generators so they execute
                    pass
            return None
        if output_schema is None:
            return res
        schema = Schema(output_schema)
        return self._rt.to_output_df(res, schema, ctx)
