"""DataFrame utilities: test comparator, partition-blob serialization, join
schema inference (reference fugue/dataframe/utils.py:39,108,150,176)."""

import base64
import math
import os
import pickle
from datetime import date, datetime
from typing import Any, Iterable, List, Optional, Tuple
from uuid import uuid4

import pyarrow as pa
import pyarrow.parquet as pq

from fugue_tpu.dataframe.array_dataframe import ArrayDataFrame
from fugue_tpu.dataframe.arrow_dataframe import ArrowDataFrame
from fugue_tpu.dataframe.dataframe import DataFrame, LocalBoundedDataFrame
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


def _comparable_key(v: Any) -> Any:
    """Total-order key over heterogenous nullable values for sorting rows."""
    if v is None:
        return (0, "")
    if isinstance(v, bool):
        return (2, str(int(v)))
    if isinstance(v, (int, float)):
        if isinstance(v, float) and math.isnan(v):
            return (1, "")
        return (3, float(v))
    if isinstance(v, (datetime, date)):
        return (4, str(v))
    if isinstance(v, bytes):
        return (5, v.hex())
    if isinstance(v, (list, tuple)):
        return (6, str([_comparable_key(x) for x in v]))
    if isinstance(v, dict):
        return (7, str(sorted((k, _comparable_key(x)) for k, x in v.items())))
    return (8, str(v))


def _rows_sorted(rows: Iterable[Any]) -> List[Any]:
    return sorted(rows, key=lambda r: [str(_comparable_key(v)) for v in r])


def _value_eq(a: Any, b: Any, digits: int) -> bool:
    if a is None or b is None:
        # NaN normalizes to None at the arrow boundary
        an = a is None or (isinstance(a, float) and math.isnan(a))
        bn = b is None or (isinstance(b, float) and math.isnan(b))
        return an and bn
    if isinstance(a, float) or isinstance(b, float):
        try:
            af, bf = float(a), float(b)
        except (TypeError, ValueError):
            return str(a) == str(b)
        if math.isnan(af) and math.isnan(bf):
            return True
        if math.isinf(af) or math.isinf(bf):
            return af == bf
        return abs(af - bf) < 10 ** (-digits) * max(1.0, abs(af), abs(bf))
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a.keys()) == set(b.keys()) and all(
            _value_eq(a[k], b[k], digits) for k in a
        )
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_value_eq(x, y, digits) for x, y in zip(a, b))
    return a == b


def df_eq(
    df: DataFrame,
    data: Any,
    schema: Any = None,
    digits: int = 8,
    check_order: bool = False,
    check_schema: bool = True,
    check_content: bool = True,
    throw: bool = False,
) -> bool:
    """Compare a DataFrame against expected data (sort-insensitive by default,
    float-tolerant) — the test backbone, parity with reference ``_df_eq``."""
    try:
        from fugue_tpu.dataframe.api import as_fugue_df

        df1 = df.as_local_bounded() if isinstance(df, DataFrame) else as_fugue_df(df).as_local_bounded()
        if isinstance(data, DataFrame):
            df2 = data.as_local_bounded()
        else:
            df2 = as_fugue_df(data, schema=schema).as_local_bounded()
        if check_schema:
            assert_or_throw(
                df1.schema == df2.schema,
                AssertionError(f"schema mismatch {df1.schema} vs {df2.schema}"),
            )
        if check_content:
            rows1 = df1.as_array(type_safe=True)
            rows2 = df2.as_array(df1.schema.names if not check_schema else None,
                                 type_safe=True)
            assert_or_throw(
                len(rows1) == len(rows2),
                AssertionError(f"count mismatch {len(rows1)} vs {len(rows2)}"),
            )
            if not check_order:
                rows1 = _rows_sorted(rows1)
                rows2 = _rows_sorted(rows2)
            for r1, r2 in zip(rows1, rows2):
                assert_or_throw(
                    len(r1) == len(r2)
                    and all(_value_eq(a, b, digits) for a, b in zip(r1, r2)),
                    AssertionError(f"row mismatch {r1} vs {r2}"),
                )
        return True
    except AssertionError:
        if throw:
            raise
        return False


# alias used inside test suites
_df_eq = df_eq


def serialize_df(
    df: Optional[DataFrame],
    threshold: int = -1,
    file_path: Optional[str] = None,
    fs: Any = None,
) -> Optional[bytes]:
    """Serialize a local-izable dataframe into a blob (arrow IPC inside
    pickle), or spill to a parquet file past ``threshold`` returning the
    pickled file reference — the zip/comap data plane (reference
    fugue/dataframe/utils.py:108)."""
    if df is None:
        return None
    table = df.as_local_bounded().as_arrow(type_safe=True)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    data = sink.getvalue().to_pybytes()
    if threshold < 0 or len(data) <= threshold:
        return pickle.dumps(("blob", data))
    assert_or_throw(
        file_path is not None, ValueError("file_path required beyond threshold")
    )
    if fs is None:
        from fugue_tpu.utils.io import default_fs

        fs = default_fs()
    fs.write_file_atomic(file_path, lambda fp: pq.write_table(table, fp))
    return pickle.dumps(("file", file_path))


def deserialize_df(
    blob: Optional[bytes], fs: Any = None
) -> Optional[LocalBoundedDataFrame]:
    if blob is None:
        return None
    kind, payload = pickle.loads(blob)
    if kind == "blob":
        with pa.ipc.open_stream(pa.BufferReader(payload)) as reader:
            table = reader.read_all()
        return ArrowDataFrame(table)
    if kind == "file":
        if fs is None:
            from fugue_tpu.utils.io import default_fs

            fs = default_fs()
        with fs.open_input_stream(payload) as fp:
            return ArrowDataFrame(pq.read_table(fp))
    raise ValueError(f"invalid serialized dataframe {kind}")


def get_join_schemas(
    df1: DataFrame, df2: DataFrame, how: str, on: Optional[Iterable[str]]
) -> Tuple[Schema, Schema]:
    """Infer (key schema, output schema) for a join (reference utils.py:176).
    When ``on`` is empty, keys default to the column-name intersection."""
    how = how.lower().replace("_", "").replace(" ", "")
    assert_or_throw(
        how
        in (
            "semi", "leftsemi", "anti", "leftanti", "inner", "leftouter",
            "rightouter", "fullouter", "cross",
        ),
        ValueError(f"invalid join type {how}"),
    )
    on = list(on) if on is not None else []
    assert_or_throw(len(on) == len(set(on)), ValueError(f"duplicated on keys {on}"))
    schema1, schema2 = df1.schema, df2.schema
    if how == "cross":
        assert_or_throw(len(on) == 0, ValueError("cross join can't have keys"))
        assert_or_throw(
            len(schema1.intersect(schema2.names)) == 0,
            ValueError("cross join dataframes can't share columns"),
        )
        return Schema(), schema1 + schema2
    if len(on) == 0:
        on = [n for n in schema1.names if n in schema2]
    assert_or_throw(len(on) > 0, SyntaxError("no join keys found"))
    missing = [k for k in on if k not in schema1.names or k not in schema2.names]
    assert_or_throw(
        len(missing) == 0,
        KeyError(f"join keys {missing} not in both dataframes"),
    )
    schema_on = schema1.extract(on)
    assert_or_throw(
        schema_on == schema2.extract(on),
        ValueError(f"join key types mismatch on {on}"),
    )
    if how in ("semi", "leftsemi", "anti", "leftanti"):
        return schema_on, schema1
    other = Schema([f for f in schema2.fields if f.name not in schema_on.names])
    return schema_on, schema1 + other


def pickle_df(df: DataFrame) -> bytes:
    return serialize_df(df)  # type: ignore


def unpickle_df(blob: bytes) -> LocalBoundedDataFrame:
    res = deserialize_df(blob)
    assert res is not None
    return res
