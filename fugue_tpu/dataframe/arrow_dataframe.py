"""Arrow-table-backed dataframe (reference arrow_dataframe.py:35) — the
canonical host-boundary format; the JAX backend materializes device blocks
from these tables."""

from typing import Any, Dict, Iterable, List, Optional

import pandas as pd
import pyarrow as pa

from fugue_tpu.dataframe.arrow_utils import (
    cast_table,
    pandas_to_table,
    rows_to_table,
    table_to_pandas,
    table_to_rows,
)
from fugue_tpu.dataframe.dataframe import DataFrame, LocalBoundedDataFrame
from fugue_tpu.schema import Schema
from fugue_tpu.utils.assertion import assert_or_throw


class ArrowDataFrame(LocalBoundedDataFrame):
    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            super().__init__(schema)
            self._native = self.schema.create_empty_arrow()
        elif isinstance(df, pa.Table):
            if schema is None:
                schema = Schema(df.schema)
                super().__init__(schema)
                if df.schema != schema.pa_schema:
                    df = df.cast(schema.pa_schema)
                self._native = df
            else:
                schema = Schema(schema)
                assert_or_throw(
                    set(schema.names) == set(df.schema.names),
                    ValueError(f"schema {schema} doesn't match table columns"),
                )
                df = df.select(schema.names)
                super().__init__(schema)
                self._native = (
                    df if df.schema == schema.pa_schema else cast_table(df, schema)
                )
        elif isinstance(df, pd.DataFrame):
            schema = None if schema is None else Schema(schema)
            table = pandas_to_table(df, schema)
            super().__init__(Schema(table.schema) if schema is None else schema)
            self._native = table
        elif isinstance(df, DataFrame):
            if schema is None:
                super().__init__(df.schema)
                self._native = df.as_arrow(type_safe=True)
            else:
                schema = Schema(schema)
                assert_or_throw(
                    set(schema.names) == set(df.schema.names),
                    ValueError(f"schema {schema} doesn't match {df.schema}"),
                )
                super().__init__(schema)
                table = df[schema.names].as_arrow(type_safe=True)
                self._native = (
                    table
                    if table.schema == schema.pa_schema
                    else cast_table(table, schema)
                )
        elif isinstance(df, Iterable):
            super().__init__(schema)
            self._native = rows_to_table(df, self.schema)
        else:
            raise ValueError(f"can't initialize ArrowDataFrame with {type(df)}")

    @property
    def native(self) -> pa.Table:
        return self._native

    @property
    def empty(self) -> bool:
        return self._native.num_rows == 0

    def count(self) -> int:
        return self._native.num_rows

    def peek_array(self) -> List[Any]:
        self.assert_not_empty()
        return next(iter(table_to_rows(self._native.slice(0, 1))))

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.exclude(cols)
        return ArrowDataFrame(self._native.select(schema.names), schema)

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        schema = self.schema.extract(cols)
        return ArrowDataFrame(self._native.select(schema.names), schema)

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        schema = self._rename_schema(columns)
        return ArrowDataFrame(self._native.rename_columns(schema.names), schema)

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self._alter_schema(columns)
        if new_schema == self.schema:
            return self
        return ArrowDataFrame(cast_table(self._native, new_schema), new_schema)

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        return self._native

    def as_pandas(self) -> pd.DataFrame:
        return table_to_pandas(self._native)

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return list(table_to_rows(self._native, columns))

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        yield from table_to_rows(self._native, columns)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        table = self._native if columns is None else self._native.select(columns)
        schema = self.schema if columns is None else self.schema.extract(columns)
        return ArrowDataFrame(table.slice(0, n), schema)
