from fugue_tpu.dataframe.array_dataframe import ArrayDataFrame
from fugue_tpu.dataframe.arrow_dataframe import ArrowDataFrame
from fugue_tpu.dataframe.dataframe import (
    DataFrame,
    LocalBoundedDataFrame,
    LocalDataFrame,
    LocalUnboundedDataFrame,
    YieldedDataFrame,
    as_fugue_df,
)
from fugue_tpu.dataframe.dataframe_iterable_dataframe import (
    IterableArrowDataFrame,
    IterablePandasDataFrame,
    LocalDataFrameIterableDataFrame,
)
from fugue_tpu.dataframe.dataframes import DataFrames
from fugue_tpu.dataframe.iterable_dataframe import IterableDataFrame
from fugue_tpu.dataframe.pandas_dataframe import PandasDataFrame
from fugue_tpu.dataframe.utils import df_eq, deserialize_df, get_join_schemas, serialize_df
import fugue_tpu.dataframe.api  # noqa: F401  (registers builtin candidates)
