"""Streams of local dataframes — process a partition as a sequence of chunks
without materializing the whole partition (reference
dataframe_iterable_dataframe.py:21; this is also the TPU long-partition
answer: blocks-per-shard streaming when a partition exceeds HBM)."""

from typing import Any, Dict, Iterable, Iterator, List, Optional

import pandas as pd
import pyarrow as pa

from fugue_tpu.dataframe.arrow_dataframe import ArrowDataFrame
from fugue_tpu.dataframe.array_dataframe import ArrayDataFrame
from fugue_tpu.dataframe.dataframe import (
    DataFrame,
    LocalBoundedDataFrame,
    LocalDataFrame,
    LocalUnboundedDataFrame,
)
from fugue_tpu.dataframe.pandas_dataframe import PandasDataFrame
from fugue_tpu.utils.assertion import assert_or_throw


class _FrameStream:
    """Peekable stream of LocalDataFrame, skipping empty frames."""

    def __init__(self, frames: Iterator[LocalDataFrame]):
        self._frames = frames
        self._buffer: List[LocalDataFrame] = []

    def peek(self) -> Optional[LocalDataFrame]:
        while not self._buffer:
            try:
                f = next(self._frames)
            except StopIteration:
                return None
            if not f.empty:
                self._buffer.append(f)
        return self._buffer[0]

    def __iter__(self) -> Iterator[LocalDataFrame]:
        while True:
            if self._buffer:
                yield self._buffer.pop(0)
            else:
                try:
                    f = next(self._frames)
                except StopIteration:
                    return
                if not f.empty:
                    yield f


class LocalDataFrameIterableDataFrame(LocalUnboundedDataFrame):
    """An unbounded local dataframe yielding LocalDataFrame chunks."""

    def __init__(self, df: Any = None, schema: Any = None):
        if df is None:
            frames: Iterator[LocalDataFrame] = iter([])
        elif isinstance(df, LocalDataFrameIterableDataFrame):
            frames = iter(df.native)
            if schema is None and df.schema_discovered:
                schema = df.schema
        elif isinstance(df, DataFrame):
            frames = iter([df.as_local_bounded()])
            if schema is None:
                schema = df.schema
        elif isinstance(df, Iterable):
            frames = iter(df)  # type: ignore
        else:
            raise ValueError(
                f"can't initialize LocalDataFrameIterableDataFrame with {type(df)}"
            )
        self._stream = _FrameStream(frames)
        if schema is None:
            # schema must come from the first non-empty frame (lazy)
            super().__init__(lambda: self._first_frame_schema())
        else:
            super().__init__(schema)

    def _first_frame_schema(self) -> Any:
        first = self._stream.peek()
        assert_or_throw(
            first is not None,
            ValueError("schema can't be inferred from an empty stream"),
        )
        return first.schema

    @property
    def native(self) -> Iterable[LocalDataFrame]:
        return self._stream

    @property
    def empty(self) -> bool:
        return self._stream.peek() is None

    def peek_array(self) -> List[Any]:
        first = self._stream.peek()
        assert_or_throw(first is not None, ValueError("dataframe is empty"))
        return first.peek_array()  # type: ignore

    def _drop_cols(self, cols: List[str]) -> DataFrame:
        schema = self.schema.exclude(cols)
        return LocalDataFrameIterableDataFrame(
            (f.drop(cols) for f in self._stream), schema  # type: ignore
        )

    def _select_cols(self, cols: List[Any]) -> DataFrame:
        schema = self.schema.extract(cols)
        return LocalDataFrameIterableDataFrame(
            (f[cols] for f in self._stream), schema  # type: ignore
        )

    def rename(self, columns: Dict[str, str]) -> DataFrame:
        schema = self._rename_schema(columns)
        return LocalDataFrameIterableDataFrame(
            (f.rename(columns) for f in self._stream), schema  # type: ignore
        )

    def alter_columns(self, columns: Any) -> DataFrame:
        new_schema = self._alter_schema(columns)
        if new_schema == self.schema:
            return self
        return LocalDataFrameIterableDataFrame(
            (f.alter_columns(columns) for f in self._stream), new_schema  # type: ignore
        )

    def as_array(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> List[Any]:
        return list(self.as_array_iterable(columns, type_safe))

    def as_array_iterable(
        self, columns: Optional[List[str]] = None, type_safe: bool = False
    ) -> Iterable[Any]:
        for f in self._stream:
            yield from f.as_array_iterable(columns, type_safe)

    def as_pandas(self) -> pd.DataFrame:
        frames = [f.as_pandas() for f in self._stream]
        if len(frames) == 0:
            return self.schema.create_empty_pandas()
        return pd.concat(frames, ignore_index=True)

    def as_arrow(self, type_safe: bool = False) -> pa.Table:
        tables = [f.as_arrow(type_safe) for f in self._stream]
        if len(tables) == 0:
            return self.schema.create_empty_arrow()
        return pa.concat_tables(tables)

    def head(
        self, n: int, columns: Optional[List[str]] = None
    ) -> LocalBoundedDataFrame:
        assert_or_throw(n >= 0, ValueError("n must be >= 0"))
        schema = self.schema if columns is None else self.schema.extract(columns)
        rows: List[Any] = []
        for f in self._stream:
            for row in f.as_array_iterable(columns, type_safe=True):
                if len(rows) >= n:
                    return ArrayDataFrame(rows, schema)
                rows.append(row)
        return ArrayDataFrame(rows, schema)


class IterablePandasDataFrame(LocalDataFrameIterableDataFrame):
    """Chunk stream where chunks are PandasDataFrames."""

    def __init__(self, df: Any = None, schema: Any = None):
        if isinstance(df, Iterable) and not isinstance(df, DataFrame):
            df = (
                f if isinstance(f, DataFrame) else PandasDataFrame(f, schema)
                for f in df  # type: ignore
            )
        super().__init__(df, schema)

    def as_pandas(self) -> pd.DataFrame:
        return super().as_pandas()


class IterableArrowDataFrame(LocalDataFrameIterableDataFrame):
    """Chunk stream where chunks are ArrowDataFrames."""

    def __init__(self, df: Any = None, schema: Any = None):
        if isinstance(df, Iterable) and not isinstance(df, DataFrame):
            df = (
                f if isinstance(f, DataFrame) else ArrowDataFrame(f, schema)
                for f in df  # type: ignore
            )
        super().__init__(df, schema)
