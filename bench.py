"""Benchmark: the BASELINE.md headline plus all five BASELINE configs.

Prints ONE json line (driver contract):
``{"metric":..., "value":..., "unit":..., "vs_baseline":..., "detail":...}``
where value is the jax engine's rows/sec on the 100M-row numeric
transform()+groupby and ``vs_baseline`` its speedup over native. The
``detail.configs`` dict carries every BASELINE.md config (1-5), each with
native/jax secs + rows/sec + speedup. Set ``BENCH_CONFIGS=lines`` to also
print one json line per config (for humans; the driver reads line 1).
The SAME headline line is printed again LAST: the driver stores only the
output tail, so the artifact stays self-contained (VERDICT r5 #8).

Env knobs: BENCH_ROWS (default 100_000_000), BENCH_GROUPS (1024),
BENCH_NATIVE_ROWS (10_000_000), BENCH_SMALL=1 (scale everything down ~100x
for a fast smoke run).
"""

import json
import os
import tempfile
import time
from typing import Any, Callable, Dict, Tuple

_SMALL = os.environ.get("BENCH_SMALL", "") in ("1", "true")

# persistent executable cache (fugue.optimize.cache.dir; this env var is
# its deprecated-alias spelling): a fresh process deserializes the
# AOT-compiled executables instead of paying XLA again — see
# detail.jax_cold_secs for THIS process's cold number (cache-hit when a
# previous bench populated the cache) and config 7_cold_start for the
# controlled fresh-process on/off comparison
os.environ.setdefault(
    "FUGUE_JAX_COMPILE_CACHE",
    os.path.join(tempfile.gettempdir(), "fugue_jax_compile_cache"),
)


# pinned native denominators (rows/sec), measured 2026-07-30 on this
# round's container (BENCH_r04 values; config 4 re-pinned the same day
# when its workload moved to the user-level zip+transform path). The
# LIVE native run keeps feeding vs_baseline — vs_baseline_pinned divides
# by these so round-over-round numbers stop tracking the ambient
# variance of the native rerun (VERDICT r4 item 6).
_PINNED_NATIVE_RPS = {
    "headline": 24_973_678.0,
    "1_map_letter_to_food": 26_600_151.0,
    "2_partition_udf": 3_118_399.0,
    "3_fuguesql_groupby": 33_436_836.0,
    "3b_sql_join": 12_610_482.0,
    "4_cotransform": 9_335.0,
    "5_e2e_parquet": 23_835_434.0,
}


def _scale(n: int) -> int:
    return max(10_000, n // 100) if _SMALL else n


def _timed(fn: Callable[[], Any], warm: int = 5) -> float:
    """Best of `warm` runs after a cold run: on a network-tunneled TPU the
    relay's transfer paths keep warming for several iterations and ambient
    load swings 2-4x, so the minimum is the reproducible statistic (the
    engine's actual cost); medians measure the tunnel's mood."""
    fn()  # cold
    samples = []
    for _ in range(warm):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return min(samples)


# HBM peak bandwidth by TPU generation (GB/s) — the roofline denominator.
# Sources: published TPU system specs (v5e 819, v5p 2765, v4 1228,
# v6e/Trillium 1640). Matched against device_kind fragments; "v5 lite"
# comes before "v5" so v5e doesn't read as v5p.
_HBM_PEAK_GBPS = (
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v5p", 2765.0),
    ("v5", 2765.0),
    ("v6", 1640.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def _platform_peak_gbps(dev: Any) -> Any:
    if dev.platform == "cpu":
        return None
    kind = str(getattr(dev, "device_kind", "")).lower()
    for frag, peak in _HBM_PEAK_GBPS:
        if frag in kind:
            return peak
    return None


def _roofline(
    build_result_frame: Callable[[], Any],
    bytes_touched: int,
    engine: Any = None,
) -> Dict[str, Any]:
    """Decompose a device pipeline's cost on a (possibly network-attached)
    TPU: measure the relay's irreducible sync+fetch latency with a tiny
    op, then the full pipeline ending in ONE derived-scalar fetch (which
    forces all queued compute through the same single sync). The
    difference is the device-resident time; bytes_touched / that time is
    a LOWER bound on achieved HBM bandwidth (bytes_touched counts each
    logical pass over the data once; XLA fusion can only reduce real
    traffic below it). Achieved GB/s is also reported as a % of the
    platform's HBM peak, and — when ``engine`` is passed — against XLA's
    OWN traffic accounting (``jit(...).lower().compile().cost_analysis()``
    of the engine programs that ran), which proves or disproves whether
    the compiler's real traffic is near the logical bound."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fugue_tpu.jax_backend.blocks import residency_arrays

    # the sync baseline must live on the SAME backend as the pipeline
    # (frames may sit on the host CPU-XLA tier, where a sync is ~free)
    probe = build_result_frame()
    blocks0 = getattr(probe, "native", None)
    if blocks0 is None or not hasattr(blocks0, "mesh") or not any(
        c.on_device for c in blocks0.columns.values()
    ):
        return {"skipped": "result frame not device-resident (fallback?)"}
    dev = blocks0.mesh.devices.flat[0]
    tiny = jax.device_put(jnp.ones((8,), jnp.float32), dev)
    jax.block_until_ready(tiny)

    def rtt_once() -> float:
        t0 = time.perf_counter()
        float(jnp.sum(tiny * np.float32(np.random.rand())))
        return time.perf_counter() - t0

    rtt_once()
    rtt = min(rtt_once() for _ in range(5))

    if engine is not None:
        # scope cost_analysis to exactly the programs this pipeline runs
        engine.reset_program_log()

    def dev_once() -> float:
        t0 = time.perf_counter()
        fr = build_result_frame()
        parts = [
            jnp.sum(a.astype(jnp.float32))
            for a in residency_arrays(fr.native)
        ]
        float(jnp.sum(jnp.stack(parts)))  # one sync drains the pipeline
        return time.perf_counter() - t0

    dev_once()  # warm (possible jit of the reduction)
    dev_plus = min(dev_once() for _ in range(5))
    device_secs = max(dev_plus - rtt, 0.0)
    peak = _platform_peak_gbps(dev)
    gbps = (
        None
        if device_secs <= 0
        else round(bytes_touched / device_secs / 1e9, 1)
    )
    out: Dict[str, Any] = {
        "backend": dev.platform,
        "relay_rtt_secs": round(rtt, 4),
        "device_plus_rtt_secs": round(dev_plus, 4),
        "device_resident_secs": round(device_secs, 4),
        "approx_bytes_touched": bytes_touched,
        "achieved_gbps_lower_bound": gbps,
        "platform_peak_gbps": peak,
        "pct_of_peak_lower_bound": (
            None
            if gbps is None or not peak
            else round(100.0 * gbps / peak, 2)
        ),
    }
    if engine is not None:
        try:
            ca = engine.program_cost_analysis()
        except Exception:  # pragma: no cover - analysis unsupported
            ca = {"flops": 0.0, "bytes_accessed": 0.0, "programs": {}}
        if ca.get("bytes_accessed"):
            xla_gbps = (
                None
                if device_secs <= 0
                else round(ca["bytes_accessed"] / device_secs / 1e9, 1)
            )
            out["xla_cost_analysis"] = {
                "flops": ca["flops"],
                "bytes_accessed": ca["bytes_accessed"],
                "programs": {
                    k: {
                        "flops": v["flops"],
                        "bytes_accessed": v["bytes_accessed"],
                    }
                    for k, v in ca["programs"].items()
                },
                "achieved_gbps_xla": xla_gbps,
                "pct_of_peak_xla": (
                    None
                    if xla_gbps is None or not peak
                    else round(100.0 * xla_gbps / peak, 2)
                ),
                # >1 means XLA's real traffic exceeds the logical
                # bytes-touched bound (e.g. a materialized one-hot): the
                # "bandwidth gap" is then compiler traffic, not an idle
                # memory system — the cost_analysis()-based proof ISSUE
                # r6 asks for when the lower bound can't be raised
                "traffic_ratio_xla_vs_logical": (
                    None
                    if not bytes_touched
                    else round(ca["bytes_accessed"] / bytes_touched, 2)
                ),
            }
    return out


def _pair(
    rows: int,
    native_fn: Callable,
    jax_fn: Callable,
    pinned_key: str = "",
) -> Dict[str, Any]:
    native_secs = _timed(native_fn)
    jax_secs = _timed(jax_fn)
    out = {
        "rows": rows,
        "native_secs": round(native_secs, 4),
        "jax_secs": round(jax_secs, 4),
        "native_rows_per_sec": round(rows / native_secs, 1),
        "jax_rows_per_sec": round(rows / jax_secs, 1),
        "speedup": round(native_secs / jax_secs, 2),
    }
    pinned = _PINNED_NATIVE_RPS.get(pinned_key)
    if pinned and not _SMALL:
        out["speedup_pinned"] = round((rows / jax_secs) / pinned, 2)
    return out


def _governance_overhead(
    pdf: Any, jax_udf: Callable, n_rows: int
) -> Dict[str, Any]:
    """Memory-governance overhead block (ISSUE r9): the SAME
    transform+groupby pipeline on a governed engine (generous
    budget_fraction — ledger + admission active, zero spills expected)
    vs a fresh ungoverned engine, plus the governed run's peak ledger
    bytes per tier and spill count. The governed headline must stay
    within noise of the ungoverned one — a regression here means the
    ledger/admission layer leaked onto the hot path."""
    import jax

    from fugue_tpu import transform
    from fugue_tpu.column import col
    from fugue_tpu.column import functions as ff
    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.execution.api import aggregate

    def run_on(eng: Any) -> float:
        src = eng.persist(eng.to_df(pdf))

        def once() -> None:
            out = transform(
                src, jax_udf, schema="k:int,v2:float", engine=eng,
                as_fugue=True,
            )
            agg = aggregate(
                out, partition_by="k",
                s=ff.sum(col("v2")), m=ff.avg(col("v2")),
                c=ff.count(col("v2")),
                engine=eng, as_fugue=True,
            )
            arrs = [
                c_.data for c_ in agg.native.columns.values() if c_.on_device
            ]
            if agg.native.row_valid is not None:  # type: ignore
                arrs.append(agg.native.row_valid)  # type: ignore
            jax.device_get(arrs)

        return _timed(once, warm=3)

    ungoverned = make_execution_engine("jax")
    governed = make_execution_engine(
        "jax", {"fugue.jax.memory.budget_fraction": 0.8}
    )
    ungoverned_secs = run_on(ungoverned)
    governed_secs = run_on(governed)
    stats = governed.memory_stats
    ratio = governed_secs / max(ungoverned_secs, 1e-9)
    within_noise = ratio < 1.15
    if not within_noise:
        import sys

        print(
            f"WARNING: governed run {ratio:.2f}x the ungoverned run "
            "(> 1.15 noise band) — memory governance overhead regressed",
            file=sys.stderr,
        )
    return {
        "rows": n_rows,
        "governed_secs": round(governed_secs, 4),
        "ungoverned_secs": round(ungoverned_secs, 4),
        "overhead_ratio": round(ratio, 3),
        "within_noise": within_noise,
        "budget_bytes": stats["budget_bytes"],
        "peak_bytes": dict(stats["peak"]),
        "spills": stats["counters"]["spills"],
        "pressure_events": stats["counters"]["pressure_events"],
        "admissions": {
            "device": stats["counters"]["admissions_device"],
            "host": stats["counters"]["admissions_host"],
        },
    }


def _observability_overhead(
    pdf: Any, jax_udf: Callable, n_rows: int
) -> Dict[str, Any]:
    """Observability overhead block (ISSUE 8): the SAME workflow
    pipeline (transform + partitioned aggregate through
    ``FugueWorkflow.run``, which is where the span instrumentation
    lives) on an obs-ON engine (tracing enabled, per-run Chrome-trace
    export to ``memory://``) vs an obs-OFF engine. The obs-on run must
    stay within 1.05x of obs-off — a regression here means span/metric
    instrumentation leaked onto the hot path."""
    from fugue_tpu.column import col
    from fugue_tpu.column import functions as ff
    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.workflow.workflow import FugueWorkflow

    rows = min(int(n_rows), 2_000_000)  # per-iteration ingest: bound it
    sub = pdf.iloc[:rows]

    def run_on(eng: Any) -> float:
        def once() -> None:
            dag = FugueWorkflow()
            df = dag.df(sub)
            out = df.transform(jax_udf, schema="k:int,v2:float")
            agg = out.partition_by("k").aggregate(
                s=ff.sum(col("v2")), m=ff.avg(col("v2")),
                c=ff.count(col("v2")),
            )
            agg.yield_dataframe_as("res", as_local=True)
            dag.run(eng)["res"].as_array()

        return _timed(once, warm=3)

    obs_off = make_execution_engine("jax")
    obs_on = make_execution_engine(
        "jax",
        {
            "fugue.obs.enabled": True,
            "fugue.obs.trace_path": "memory://bench_obs_traces",
        },
    )
    obs_off_secs = run_on(obs_off)
    obs_on_secs = run_on(obs_on)
    ratio = obs_on_secs / max(obs_off_secs, 1e-9)
    within_noise = ratio <= 1.05
    if not within_noise:
        import sys

        print(
            f"WARNING: obs-on run {ratio:.2f}x the obs-off run "
            "(> 1.05 band) — observability overhead regressed",
            file=sys.stderr,
        )
    snap = obs_on.metrics.snapshot()
    exported = sum(
        s["value"]
        for s in (
            snap.get("fugue_obs_traces_exported_total", {}).get("samples")
            or []
        )
    )
    return {
        "rows": rows,
        "obs_on_secs": round(obs_on_secs, 4),
        "obs_off_secs": round(obs_off_secs, 4),
        "overhead_ratio": round(ratio, 3),
        "within_noise": within_noise,
        "traces_exported": int(exported),
        "compile_cache": obs_on.compile_cache_stats,
    }


def _profiler_overhead(
    pdf: Any, jax_udf: Callable, n_rows: int
) -> Dict[str, Any]:
    """Profiler overhead block (ISSUE 14): the SAME workflow pipeline as
    ``detail.observability`` with the per-task profiler ON
    (``fugue.obs.profile`` + ``fugue.obs.enabled``) vs everything OFF.
    The profiled run must stay within 1.05x — the profiler's per-task
    row counts, byte estimates and counter sampling live at task
    granularity, not per row, so the bar is the same as obs alone."""
    from fugue_tpu.column import col
    from fugue_tpu.column import functions as ff
    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.workflow.workflow import FugueWorkflow

    rows = min(int(n_rows), 2_000_000)  # per-iteration ingest: bound it
    sub = pdf.iloc[:rows]
    last_profile: Dict[str, Any] = {}

    def run_on(eng: Any, capture: bool = False) -> float:
        def once() -> None:
            dag = FugueWorkflow()
            df = dag.df(sub)
            out = df.transform(jax_udf, schema="k:int,v2:float")
            agg = out.partition_by("k").aggregate(
                s=ff.sum(col("v2")), m=ff.avg(col("v2")),
                c=ff.count(col("v2")),
            )
            agg.yield_dataframe_as("res", as_local=True)
            res = dag.run(eng)
            res["res"].as_array()
            if capture:
                prof = res.profile()
                if prof is not None:
                    last_profile["tasks"] = len(prof.records)
                    last_profile["top"] = prof.top_tasks(1)

        return _timed(once, warm=3)

    prof_off = make_execution_engine("jax")
    prof_on = make_execution_engine(
        "jax",
        {"fugue.obs.enabled": True, "fugue.obs.profile": True},
    )
    off_secs = run_on(prof_off)
    on_secs = run_on(prof_on, capture=True)
    ratio = on_secs / max(off_secs, 1e-9)
    within_noise = ratio <= 1.05
    if not within_noise:
        import sys

        print(
            f"WARNING: profiler-on run {ratio:.2f}x the profiler-off run "
            "(> 1.05 band) — per-task profiler overhead regressed",
            file=sys.stderr,
        )
    return {
        "rows": rows,
        "profile_on_secs": round(on_secs, 4),
        "profile_off_secs": round(off_secs, 4),
        "overhead_ratio": round(ratio, 3),
        "within_noise": within_noise,
        "tasks_profiled": last_profile.get("tasks", 0),
        "top_task": (last_profile.get("top") or [{}])[0],
    }


def _optimizer_pipeline_bench(n: int, warm: int = 3) -> Dict[str, Any]:
    """ISSUE 10: narrow-consumer e2e parquet pipeline, optimizer on vs
    off. The WIDE file (8 columns) feeds load -> filter -> select(k, v)
    -> SQL groupby through the WORKFLOW layer (the optimizer rewrites
    the DAG; direct engine-API calls bypass it). With ``fugue.optimize``
    on, projection pushdown threads the 2-column requirement through the
    filter into the streamed ingest's narrow-load planner, so the 6 pad
    columns are never decoded or staged; off, the filter materializes
    the full 8-column frame first. The acceptance bar is on/off > 1.2x."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.column import col
    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.optimize import get_plan_cache
    from fugue_tpu.workflow.workflow import FugueWorkflow

    rng = np.random.default_rng(17)
    tmp = tempfile.mkdtemp(prefix="fugue_bench_opt_")
    src = os.path.join(tmp, "wide.parquet")
    wide = pd.DataFrame(
        {
            "k": rng.integers(0, 256, n).astype(np.int64),
            "v": rng.random(n),
        }
    )
    for i in range(6):
        wide[f"pad{i}"] = rng.random(n)
    wide.to_parquet(src, row_group_size=max(n // 32, 10_000))

    io_conf = {"fugue.jax.io.batch_rows": max(n // 8, 65_536)}
    engines = {
        mode: make_execution_engine(
            "jax", {**io_conf, "fugue.optimize": mode}
        )
        for mode in ("off", "on")
    }

    def run(mode: str) -> None:
        dag = FugueWorkflow()
        df = dag.load(src).filter(col("k") < 128).select("k", "v")
        dag.select(
            "SELECT k, SUM(v) AS s, COUNT(*) AS c FROM", df, "GROUP BY k"
        ).yield_dataframe_as("out", as_local=True)
        dag.run(engines[mode])

    off_secs = _timed(lambda: run("off"), warm=warm)
    on_secs = _timed(lambda: run("on"), warm=warm)
    speedup = round(off_secs / max(on_secs, 1e-9), 2)
    if speedup < 1.2:
        import sys

        print(
            f"WARNING: optimizer-on narrow-consumer pipeline only "
            f"{speedup:.2f}x optimizer-off (acceptance bar is 1.2x)",
            file=sys.stderr,
        )
    return {
        "rows": n,
        "columns_total": 8,
        "columns_consumed": 2,
        "narrow_off_secs": round(off_secs, 4),
        "narrow_on_secs": round(on_secs, 4),
        "narrow_speedup": speedup,
        "plan_cache": get_plan_cache().stats(),
    }


def _bench_headline() -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pandas as pd

    from fugue_tpu import transform
    from fugue_tpu.column import col
    from fugue_tpu.column import functions as ff
    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.execution.api import aggregate

    n_rows = _scale(int(os.environ.get("BENCH_ROWS", 100_000_000)))
    n_groups = int(os.environ.get("BENCH_GROUPS", 1024))
    n_native = min(
        n_rows, _scale(int(os.environ.get("BENCH_NATIVE_ROWS", 10_000_000)))
    )

    rng = np.random.default_rng(42)
    # float32 + int32: TPU-friendly dtypes (f64 has no TPU hardware path)
    keys = rng.integers(0, n_groups, n_rows).astype(np.int32)
    values = rng.random(n_rows).astype(np.float32)

    # ---- native (pandas) baseline ---------------------------------------
    pdf_small = pd.DataFrame({"k": keys[:n_native], "v": values[:n_native]})

    def pandas_udf(df: pd.DataFrame) -> pd.DataFrame:
        return df.assign(v2=df["v"] * 2.0 + 1.0)

    native = make_execution_engine("native")

    def run_native() -> None:
        out = transform(pdf_small, pandas_udf, schema="*,v2:float",
                        engine=native, as_fugue=True)
        agg = aggregate(
            out, partition_by="k",
            s=ff.sum(col("v2")), m=ff.avg(col("v2")), c=ff.count(col("v2")),
            engine=native, as_fugue=True,
        )
        agg.as_local()

    native_samples = []
    for _ in range(2):
        t0 = time.perf_counter()
        run_native()
        native_samples.append(time.perf_counter() - t0)
    native_secs = min(native_samples)  # same statistic as the jax side
    native_rps = n_native / native_secs

    # ---- jax engine (device) --------------------------------------------
    jdf_pd = pd.DataFrame({"k": keys, "v": values})
    engine = make_execution_engine("jax")

    def jax_udf(arrs: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {"k": arrs["k"], "v2": arrs["v"] * jnp.float32(2.0) + 1.0}

    # device placement outside the timed region, matching the reference
    # measurement shape (data already in the engine): persist forces the
    # lazy ingest NOW so jax_cold_secs measures trace+compile (a cache hit
    # when fugue.jax.compile.cache is warm), not the one-time staging of
    # 800MB over the host->device link
    src = engine.persist(engine.to_df(jdf_pd))

    def run_once() -> float:
        t0 = time.perf_counter()
        out = transform(src, jax_udf, schema="k:int,v2:float", engine=engine,
                        as_fugue=True)
        agg = aggregate(
            out, partition_by="k",
            s=ff.sum(col("v2")), m=ff.avg(col("v2")), c=ff.count(col("v2")),
            engine=engine, as_fugue=True,
        )
        # materialize the (small) result to host — the honest endpoint,
        # same as the native path's as_local(); block_until_ready alone is
        # not trustworthy on relayed TPU backends. One async wave.
        arrs = [c.data for c in agg.native.columns.values() if c.on_device]
        if agg.native.row_valid is not None:  # type: ignore
            arrs.append(agg.native.row_valid)  # type: ignore
        jax.device_get(arrs)
        return time.perf_counter() - t0

    cold_secs = run_once()  # includes jit compilation at full shapes
    warm = [run_once() for _ in range(5)]
    jax_secs = min(warm)  # best-of: see _timed — min is the reproducible
    # statistic on a tunneled TPU; medians measure ambient relay load
    jax_rps = n_rows / jax_secs

    def build_frame() -> Any:
        out = transform(src, jax_udf, schema="k:int,v2:float",
                        engine=engine, as_fugue=True)
        return aggregate(
            out, partition_by="k",
            s=ff.sum(col("v2")), m=ff.avg(col("v2")), c=ff.count(col("v2")),
            engine=engine, as_fugue=True,
        )

    # transform reads k+v, writes v2; groupby reads k+v2 (5 x 4B streams)
    roofline = _roofline(build_frame, n_rows * 20, engine=engine)

    memory_block = _governance_overhead(
        pd.DataFrame({"k": keys[:n_native], "v": values[:n_native]}),
        jax_udf,
        n_native,
    )

    observability_block = _observability_overhead(
        pd.DataFrame({"k": keys[:n_native], "v": values[:n_native]}),
        jax_udf,
        n_native,
    )

    profiler_block = _profiler_overhead(
        pd.DataFrame({"k": keys[:n_native], "v": values[:n_native]}),
        jax_udf,
        n_native,
    )

    optimizer_block = _optimizer_pipeline_bench(_scale(2_000_000))

    return {
        "metric": "transform_groupby_rows_per_sec",
        "value": round(jax_rps, 1),
        "unit": "rows/sec",
        "vs_baseline": round(jax_rps / native_rps, 2),
        "vs_baseline_pinned": (
            None  # pinned denominators are full-scale measurements
            if _SMALL
            else round(jax_rps / _PINNED_NATIVE_RPS["headline"], 2)
        ),
        "detail": {
            "rows_jax": n_rows,
            "rows_native": n_native,
            "groups": n_groups,
            "jax_secs": round(jax_secs, 4),
            "jax_cold_secs": round(cold_secs, 4),
            "native_secs": round(native_secs, 4),
            "native_rows_per_sec": round(native_rps, 1),
            "roofline": roofline,
            "strategy_counts": dict(engine.strategy_counts),
            "memory": memory_block,
            "observability": observability_block,
            "profiler": profiler_block,
            "optimizer": optimizer_block,
            "devices": len(jax.devices()),
            "platform": jax.devices()[0].platform,
            "notes": (
                "vs_baseline uses the same min-of-warm statistic on both "
                "sides; vs_baseline_pinned divides by the dated pinned "
                "denominator (_PINNED_NATIVE_RPS) so rounds compare "
                "without the native rerun's ambient variance. "
                "jax_cold_secs is THIS process's first full-shape run "
                "AFTER a forcing persist: rounds 1-4 reported 24-93s "
                "here, which profiling showed was the 800MB host->device "
                "staging completing lazily over the ~10MB/s network "
                "relay inside the first timed run (the relay acks "
                "block_until_ready optimistically; persist now forces "
                "residency with a derived-value fetch, so staging lands "
                "in setup where the reference's in-memory input also "
                "lives). The residual cold ~2-9s is trace + persistent-"
                "compile-cache load + first dispatch. detail.roofline "
                "splits warm time into the relay's sync round trip "
                "(~0.11s on this tunnel, microseconds on locally-"
                "attached TPUs) vs device-resident compute, with a "
                "bytes-touched lower bound on achieved bandwidth. "
                "Small/IO-bound configs run on the engine's host "
                "CPU-XLA placement tier (fugue.jax.placement=auto): "
                "per-query transfer over the network-attached TPU link "
                "dominates any accelerator win at those sizes — 3b's "
                "roofline shows exactly that tradeoff."
            ),
        },
    }


def _config1_map_letter_to_food() -> Dict[str, Any]:
    """BASELINE config 1: the README map_letter_to_food transform (string
    mapping UDF). Each engine runs its idiomatic UDF (same convention as
    configs 2/5): pandas ``.map`` on native; the dictionary-code compiled
    map ABI on jax — codes pass through unchanged and the 3-entry decode
    table is remapped on host, so the transform is O(|dictionary|) host
    work plus the arrow export."""
    import jax
    import numpy as np
    import pandas as pd

    from fugue_tpu import transform
    from fugue_tpu.execution import make_execution_engine

    n = _scale(2_000_000)
    mapping = {"A": "Apple", "B": "Banana", "C": "Carrot"}
    pdf = pd.DataFrame(
        {"id": np.arange(n), "value": np.random.default_rng(0).choice(
            ["A", "B", "C"], n)}
    )

    def map_letter_to_food(df: pd.DataFrame, mp: dict) -> pd.DataFrame:
        df["value"] = df["value"].map(mp)
        return df

    def jax_map_letter(arrs: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        d = arrs["_value_dict"]
        remapped = np.array(
            [mapping.get(s, s) for s in d.tolist()], dtype=object
        )
        return {
            "id": arrs["id"],
            "value": arrs["value"],
            "_value_dict": remapped,
        }

    native = make_execution_engine("native")
    jax_e = make_execution_engine("jax")
    jsrc = jax_e.to_df(pdf)  # pre-staged source, same as configs 2/3

    def run_native() -> None:
        transform(
            pdf, map_letter_to_food, schema="*",
            params=dict(mp=mapping), engine=native, as_fugue=True,
        ).as_local()

    def run_jax() -> None:
        transform(
            jsrc, jax_map_letter, schema="*", engine=jax_e, as_fugue=True
        ).as_local()

    res = _pair(n, run_native, run_jax, "1_map_letter_to_food")
    # VERDICT r5 #7: quantify the auto-placement tradeoff per round. The
    # row above runs placement=auto (this config lands on the host
    # CPU-XLA tier); rerun with the accelerator tier FORCED so both sides
    # of the policy are measured, not asserted. On CPU-only boxes the
    # "device" tier IS the host mesh, so the two rows converge.
    forced = make_execution_engine("jax", {"fugue.jax.placement": "device"})
    fsrc = forced.persist(forced.to_df(pdf))  # stage outside the timing

    def run_forced() -> None:
        transform(
            fsrc, jax_map_letter, schema="*", engine=forced, as_fugue=True
        ).as_local()

    forced_secs = _timed(run_forced)
    res["placement"] = {
        "auto": {
            "jax_secs": res["jax_secs"],
            "backend": jsrc.native.mesh.devices.flat[0].platform,
        },
        "tpu": {
            "jax_secs": round(forced_secs, 4),
            "jax_rows_per_sec": round(n / forced_secs, 1),
            "backend": fsrc.native.mesh.devices.flat[0].platform,
        },
    }
    return res


def _config2_partition_udf() -> Dict[str, Any]:
    """BASELINE config 2: 10M-row vectorized UDF with partition_by."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pandas as pd

    from fugue_tpu import transform
    from fugue_tpu.execution import make_execution_engine

    n = _scale(10_000_000)
    rng = np.random.default_rng(1)
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 512, n).astype(np.int32),
            "v": rng.random(n).astype(np.float32),
        }
    )

    def pandas_udf(df: pd.DataFrame) -> pd.DataFrame:
        return df.assign(z=(df["v"] - df["v"].mean()))

    def jax_udf(arrs: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        seg, num, valid = (
            arrs["_segment_ids"], arrs["_num_segments"], arrs["_row_valid"]
        )
        v = jnp.where(valid, arrs["v"], 0.0)
        cnt = jax.ops.segment_sum(
            jnp.where(valid, 1.0, 0.0), seg, num_segments=num
        )
        mean = jax.ops.segment_sum(v, seg, num_segments=num) / jnp.maximum(
            cnt, 1.0
        )
        return {
            "k": arrs["k"], "v": arrs["v"],
            "z": arrs["v"] - mean[jnp.clip(seg, 0, num - 1)],
        }

    native = make_execution_engine("native")
    jax_e = make_execution_engine("jax")
    jsrc = jax_e.to_df(pdf)

    def run_native() -> None:
        transform(
            pdf, pandas_udf, schema="*,z:float",
            partition={"by": ["k"]}, engine=native, as_fugue=True,
        ).as_local()

    def run_jax() -> None:
        out = transform(
            jsrc, jax_udf, schema="k:int,v:float,z:float",
            partition={"by": ["k"]}, engine=jax_e, as_fugue=True,
        )
        import jax as _j

        # honest endpoint: ALL device output columns come back (same
        # statistic as the headline), not just the first
        arrs = [c.data for c in out.native.columns.values() if c.on_device]
        if out.native.row_valid is not None:
            arrs.append(out.native.row_valid)
        _j.device_get(arrs)

    return _pair(n, run_native, run_jax, "2_partition_udf")


def _config3_fuguesql_groupby() -> Dict[str, Any]:
    """BASELINE config 3: FugueSQL SELECT + GROUP BY sum/mean/count."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.workflow.api import raw_sql

    n = _scale(10_000_000)
    rng = np.random.default_rng(2)
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 256, n).astype(np.int32),
            "v": rng.random(n).astype(np.float32),
        }
    )
    native = make_execution_engine("native")
    jax_e = make_execution_engine("jax")
    jsrc = jax_e.to_df(pdf)

    def run(engine: Any, src: Any) -> None:
        raw_sql(
            "SELECT k, SUM(v) AS s, AVG(v) AS m, COUNT(*) AS c FROM", src,
            "GROUP BY k", engine=engine, as_fugue=True,
        ).as_local()

    return _pair(
        n, lambda: run(native, pdf), lambda: run(jax_e, jsrc),
        "3_fuguesql_groupby",
    )


def _config3b_sql_join() -> Dict[str, Any]:
    """Supplementary (verdict r3 item 3): FugueSQL two-table equi-join +
    GROUP BY — the shape that lowers through the device relational layer
    (joins in relational.py) instead of the host SELECT runner."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.workflow.api import raw_sql

    n = _scale(5_000_000)
    rng = np.random.default_rng(5)
    facts = pd.DataFrame(
        {
            "k": rng.integers(0, 256, n).astype(np.int32),
            "v": rng.random(n).astype(np.float32),
        }
    )
    dims = pd.DataFrame(
        {
            "k": np.arange(256, dtype=np.int32),
            "w": rng.random(256).astype(np.float32),
        }
    )
    native = make_execution_engine("native")
    jax_e = make_execution_engine("jax")
    jf, jd = jax_e.to_df(facts), jax_e.to_df(dims)

    def run(engine: Any, f: Any, d: Any) -> Any:
        return raw_sql(
            "SELECT f.k, SUM(v) AS s, AVG(w) AS m, COUNT(*) AS c FROM", f,
            "AS f JOIN", d, "AS d ON f.k = d.k GROUP BY f.k",
            engine=engine, as_fugue=True,
        )

    res = _pair(
        n,
        lambda: run(native, facts, dims).as_local(),
        lambda: run(jax_e, jf, jd).as_local(),
        "3b_sql_join",
    )
    # snapshot BEFORE the roofline probe re-runs the query
    res["jax_fallbacks"] = dict(jax_e.fallbacks)
    # join reads k+v, gathers w + validity; groupby reads k+v+w
    res["roofline"] = _roofline(lambda: run(jax_e, jf, jd), n * 20)
    return res


def _config4_cotransform() -> Dict[str, Any]:
    """BASELINE config 4: cotransform inner zip+comap of two partitioned
    dataframes (the path rebuilt without serialization)."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.execution import make_execution_engine

    groups = 2_000 if not _SMALL else 100
    per = 50
    n = groups * per
    rng = np.random.default_rng(3)
    a = pd.DataFrame(
        {
            "k": np.repeat(np.arange(groups, dtype=np.int64), per),
            "v": rng.random(n),
        }
    )
    b = pd.DataFrame(
        {
            "k": np.arange(groups, dtype=np.int64),
            "w": rng.random(groups),
        }
    )

    def cm_pandas(dfa: pd.DataFrame, dfb: pd.DataFrame) -> pd.DataFrame:
        va, vb = dfa, dfb
        return pd.DataFrame(
            {
                "k": [int(va.k.iloc[0])],
                "s": [float(va.v.sum() + (vb.w.sum() if len(vb) else 0.0))],
            }
        )

    import jax as _jax
    import jax.numpy as jnp

    def cm_jax(
        da: Dict[str, _jax.Array], db: Dict[str, _jax.Array]
    ) -> Dict[str, _jax.Array]:
        # the compiled-comap ABI: per-key work as segment reductions over
        # the shared segment space (comap_compiled.py)
        S = da["_num_segments"]
        sa = _jax.ops.segment_sum(
            jnp.where(da["_row_valid"], da["v"], 0.0),
            da["_segment_ids"], num_segments=S,
        )
        sb = _jax.ops.segment_sum(
            jnp.where(db["_row_valid"], db["w"], 0.0),
            db["_segment_ids"], num_segments=S,
        )
        k = _jax.ops.segment_max(
            jnp.where(da["_row_valid"], da["k"].astype(jnp.int32), -(2**31)),
            da["_segment_ids"], num_segments=S,
        )
        return {"k": k, "s": sa + sb}

    def run(engine: Any, cm: Any) -> None:
        from fugue_tpu.workflow import FugueWorkflow

        dag = FugueWorkflow()
        za = dag.df(a, "k:long,v:double")
        zb = dag.df(b, "k:long,w:double")
        z = za.partition_by("k").zip(zb)
        z.transform(cm, schema="k:long,s:double").yield_dataframe_as(
            "out", as_local=True
        )
        dag.run(engine)

    native = make_execution_engine("native")
    jax_e = make_execution_engine("jax")
    res = _pair(
        n, lambda: run(native, cm_pandas), lambda: run(jax_e, cm_jax),
        "4_cotransform",
    )
    res["jax_fallbacks"] = dict(jax_e.fallbacks)
    return res


def _config5_e2e_parquet() -> Dict[str, Any]:
    """BASELINE config 5: load parquet -> transform -> groupby -> save."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.column import col
    from fugue_tpu.column import functions as ff
    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.execution.api import aggregate
    from fugue_tpu import transform

    n = _scale(5_000_000)
    rng = np.random.default_rng(4)
    pdf = pd.DataFrame(
        {
            "k": rng.integers(0, 128, n).astype(np.int32),
            "v": rng.random(n).astype(np.float32),
        }
    )
    tmp = tempfile.mkdtemp(prefix="fugue_bench_")
    src_path = os.path.join(tmp, "src.parquet")
    pdf.to_parquet(src_path)

    def pandas_udf(df: pd.DataFrame) -> pd.DataFrame:
        return df.assign(v2=df["v"] * 0.5)

    import jax as _jax
    import jax.numpy as jnp

    def jax_udf(arrs: Dict[str, _jax.Array]) -> Dict[str, _jax.Array]:
        return {"k": arrs["k"], "v2": arrs["v"] * jnp.float32(0.5)}

    engines = {
        "native": make_execution_engine("native"),
        "jax": make_execution_engine("jax"),
        # streamed ingest/save: record-batch decode overlaps per-shard
        # device staging (fugue.jax.io.batch_rows; ISSUE 2 tentpole)
        "jax_streamed": make_execution_engine(
            "jax", {"fugue.jax.io.batch_rows": max(n // 16, 65_536)}
        ),
    }

    def run(engine: Any, udf: Any, schema: str, out_name: str) -> None:
        e = engines[engine]  # reuse: jit caches live on the engine
        df = e.load_df(src_path, format_hint="parquet")
        out = transform(df, udf, schema=schema, engine=e, as_fugue=True)
        agg = aggregate(
            out, partition_by="k",
            s=ff.sum(col("v2")), c=ff.count(col("v2")),
            engine=e, as_fugue=True,
        )
        e.save_df(agg, os.path.join(tmp, out_name), format_hint="parquet")

    def _drain(df: Any) -> Any:
        """Force device residency so a phase boundary is honest (lazy
        ingest + async dispatch otherwise push work into later phases)."""
        import jax as __jax

        blocks = getattr(df, "blocks", None)
        if blocks is not None and not callable(blocks):
            from fugue_tpu.jax_backend.blocks import residency_arrays

            for arr in residency_arrays(blocks):
                __jax.block_until_ready(arr)
        return df

    def run_phases(engine: Any, udf: Any, schema: str, out_name: str) -> Dict[str, float]:
        """One decomposed pass: per-phase seconds with forced phase
        boundaries. Comparing `sum(phases)` with the pipelined e2e time
        (which never forces boundaries) makes the load/stage/save
        overlap win visible in the artifact."""
        e = engines[engine]
        t0 = time.perf_counter()
        df = _drain(e.load_df(src_path, format_hint="parquet"))
        t1 = time.perf_counter()
        out = transform(df, udf, schema=schema, engine=e, as_fugue=True)
        agg = _drain(aggregate(
            out, partition_by="k",
            s=ff.sum(col("v2")), c=ff.count(col("v2")),
            engine=e, as_fugue=True,
        ))
        t2 = time.perf_counter()
        e.save_df(agg, os.path.join(tmp, out_name), format_hint="parquet")
        t3 = time.perf_counter()
        return {
            "load_secs": round(t1 - t0, 4),
            "compute_secs": round(t2 - t1, 4),
            "save_secs": round(t3 - t2, 4),
            "sum_secs": round(t3 - t0, 4),
        }

    res = _pair(
        n,
        lambda: run("native", pandas_udf, "*,v2:float", "out_native.parquet"),
        lambda: run(
            "jax", jax_udf, "k:int,v2:float", "out_jax.parquet"
        ),
        pinned_key="5_e2e_parquet",
    )
    streamed_secs = _timed(
        lambda: run("jax_streamed", jax_udf, "k:int,v2:float",
                    "out_jax_s.parquet")
    )
    res["jax_streamed_secs"] = round(streamed_secs, 4)
    res["jax_streamed_rows_per_sec"] = round(n / streamed_secs, 1)
    res["streamed_vs_eager"] = round(res["jax_secs"] / streamed_secs, 2)
    res["phases"] = {
        name: run_phases(name, udf, schema, out)
        for name, udf, schema, out in [
            ("native", pandas_udf, "*,v2:float", "out_native.parquet"),
            ("jax", jax_udf, "k:int,v2:float", "out_jax.parquet"),
            ("jax_streamed", jax_udf, "k:int,v2:float", "out_jax_s.parquet"),
        ]
    }
    # ISSUE 10: optimizer on/off dual rows — the workflow-layer
    # narrow-consumer variant of this pipeline at the same scale
    res["optimizer"] = _optimizer_pipeline_bench(n)
    return res


def _config6_serving_daemon() -> Dict[str, Any]:
    """Sustained-throughput serving scenario (ISSUE r11): concurrent
    clients over real HTTP against ONE in-process daemon with a shared
    persistent jax engine — each client's hot table is saved once and
    then queried repeatedly (groupby SQL over the device-resident
    catalog frame, no re-ingest). Reports queries/sec and p50/p99
    request latency alongside the batch configs' rows/sec."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.serve import ServeClient, ServeDaemon

    clients = 4
    queries_per_client = 8
    rows = _scale(1_000_000)
    agg_sql = "SELECT k, SUM(v) AS s, COUNT(*) AS c FROM t GROUP BY k"
    out: Dict[str, Any] = {
        "clients": clients,
        "queries_per_client": queries_per_client,
        "rows_per_table": rows,
        # this block measures the default FIFO queue; config 12 runs the
        # predictive scheduler, so the headline rows stay comparable
        "scheduler": "fifo",
    }
    import threading as _threading

    # result cache OFF here: this block's qps/p50/p99 measure serving
    # EXECUTION (comparable with prior rounds); the cached fast path is
    # measured separately by warm_resubmission below
    with ServeDaemon(
        {
            "fugue.serve.max_concurrent": clients,
            "fugue.serve.result_cache": False,
        }
    ) as daemon:
        host, port = daemon.address
        rng = np.random.default_rng(11)
        latencies: list = []
        errors: list = []
        lat_lock = _threading.Lock()

        # hot-table setup + program warmup, UNMEASURED: each client's
        # table is saved once and stays device-resident in the catalog;
        # the timed loop below is pure serving traffic
        handles = []
        for i in range(clients):
            c = ServeClient(host, port, timeout=600)
            sid = c.create_session()
            pdf = pd.DataFrame(
                {
                    "k": rng.integers(0, 64, rows).astype(np.int64),
                    "v": rng.random(rows),
                }
            )
            daemon.sessions.get(sid).save_table(
                "t", daemon.engine.to_df(pdf)
            )
            c.sql(sid, agg_sql)  # warm the compiled programs
            handles.append((c, sid))

        def one_client(c: Any, sid: str) -> None:
            try:
                mine = []
                for _ in range(queries_per_client):
                    t0 = time.perf_counter()
                    r = c.sql(sid, agg_sql)
                    mine.append((time.perf_counter() - t0) * 1000.0)
                    if r["status"] != "done":
                        errors.append(r.get("error"))
                with lat_lock:
                    latencies.extend(mine)
                c.close_session(sid)
            except Exception as ex:  # pragma: no cover - surfaced in json
                errors.append(repr(ex))

        threads = [
            _threading.Thread(target=one_client, args=h) for h in handles
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        status = daemon.status()
        out["errors"] = errors
        total = clients * queries_per_client
        out["queries"] = total
        out["wall_secs"] = round(wall, 4)
        out["queries_per_sec"] = round(total / wall, 2) if wall > 0 else 0.0
        if latencies:
            out["p50_ms"] = round(float(np.percentile(latencies, 50)), 2)
            out["p99_ms"] = round(float(np.percentile(latencies, 99)), 2)
            out["mean_ms"] = round(float(np.mean(latencies)), 2)
        out["jobs"] = status["jobs"]
        out["fault_stats"] = status["fault_stats"]
    out["warm_resubmission"] = _serving_warm_resubmission(
        _scale(1_000_000), agg_sql
    )
    out["restart_recovery"] = _serving_restart_recovery(
        clients, _scale(200_000), agg_sql
    )
    return out


def _serving_warm_resubmission(rows: int, agg_sql: str) -> Dict[str, Any]:
    """Warm-resubmission scenario (ISSUE 10): the SAME query resubmitted
    on a hot session answers from the cross-request plan/result cache —
    no Python planning, no dispatch, no XLA compile. Runs its own
    default-conf daemon (the cache is ON by default; the main qps block
    above disables it to measure execution). Reports the plan-cache hit
    rate, the p50 latency delta vs the first (executed) submission, and
    the engine's plan-cache miss delta during the warm loop (the
    zero-recompiles proof)."""
    import numpy as np
    import pandas as pd

    from fugue_tpu.serve import ServeClient, ServeDaemon

    repeats = 16
    with ServeDaemon({"fugue.serve.max_concurrent": 2}) as daemon:
        host, port = daemon.address
        c = ServeClient(host, port, timeout=600)
        sid = c.create_session()
        rng = np.random.default_rng(23)
        pdf = pd.DataFrame(
            {
                "k": rng.integers(0, 64, rows).astype(np.int64),
                "v": rng.random(rows),
            }
        )
        daemon.sessions.get(sid).save_table("t", daemon.engine.to_df(pdf))
        t0 = time.perf_counter()
        first = c.sql(sid, agg_sql)
        first_ms = (time.perf_counter() - t0) * 1000.0
        assert first["status"] == "done", first
        plan_misses_before = daemon.engine.plan_cache_stats["misses"]
        warm_ms = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            r = c.sql(sid, agg_sql)
            warm_ms.append((time.perf_counter() - t0) * 1000.0)
            assert r["status"] == "done", r
        plan_miss_delta = (
            daemon.engine.plan_cache_stats["misses"] - plan_misses_before
        )
        st = daemon.status()
        sr = st["plan_cache"]["serve_result"]
        looked_up = sr.get("hit", 0) + sr.get("miss", 0)
        c.close_session(sid)
    p50 = float(np.percentile(warm_ms, 50))
    return {
        "rows": rows,
        "resubmissions": repeats,
        "first_ms": round(first_ms, 2),
        "warm_p50_ms": round(p50, 2),
        "p50_latency_delta_ms": round(first_ms - p50, 2),
        "warm_speedup": round(first_ms / max(p50, 1e-9), 2),
        "result_cache_hits": sr.get("hit", 0),
        "plan_cache_hit_rate": (
            round(sr.get("hit", 0) / looked_up, 4) if looked_up else 0.0
        ),
        "recompiles_during_warm": plan_miss_delta,
    }


def _serving_restart_recovery(
    tenants: int, rows: int, agg_sql: str
) -> Dict[str, Any]:
    """Restart-recovery scenario (ISSUE 7 + 11): a DURABLE daemon holding
    one hot table per tenant — now also backed by the persistent
    executable cache — is hard-killed mid-serving, then restarted on the
    same state path. Reports time-to-ready (journal load + session
    rehydration + executable pre-warm), the recovered session/hot-table
    counts, and ``time_to_first_query`` SPLIT into journal-reload /
    cache-load / compile / dispatch phases (the compile phase must read
    ~0 when the pre-warm did its job)."""
    import tempfile

    import numpy as np
    import pandas as pd

    from fugue_tpu.optimize import flush_persists, get_plan_cache
    from fugue_tpu.serve import ServeClient, ServeDaemon

    out: Dict[str, Any] = {"tenants": tenants, "rows_per_table": rows}
    with tempfile.TemporaryDirectory() as state_dir:
        conf = {
            "fugue.serve.max_concurrent": tenants,
            "fugue.serve.state_path": os.path.join(state_dir, "state"),
            # ISSUE 11: the executable disk tier + daemon pre-warm make
            # the restart's first query compile-free
            "fugue.optimize.cache.dir": os.path.join(state_dir, "xc"),
        }
        d1 = ServeDaemon(conf).start()
        host, port = d1.address
        rng = np.random.default_rng(7)
        sids = []
        for _ in range(tenants):
            c = ServeClient(host, port, timeout=600)
            sid = c.create_session()
            pdf = pd.DataFrame(
                {
                    "k": rng.integers(0, 64, rows).astype(np.int64),
                    "v": rng.random(rows),
                }
            )
            d1.sessions.get(sid).save_table("t", d1.engine.to_df(pdf))
            sids.append(sid)
        for sid in sids:
            ServeClient(host, port, timeout=600).sql(sid, agg_sql)
        flush_persists()  # executables durable before the "kill -9"
        d1._hard_kill()  # no drain, no final journal write
        # the plan cache is process-wide: clearing it makes the restart
        # below equivalent to a fresh process (disk is the only carry)
        get_plan_cache().clear()

        t0 = time.perf_counter()
        d2 = ServeDaemon(conf).start()
        out["time_to_healthy_secs"] = round(time.perf_counter() - t0, 4)
        while not d2.ready and time.perf_counter() - t0 < 120:
            time.sleep(0.01)
        out["time_to_ready_secs"] = round(time.perf_counter() - t0, 4)
        try:
            c2 = ServeClient(host, d2.address[1], timeout=600)
            st = c2.status()
            out["recovered_sessions"] = st["recovery"]["sessions"]
            # first query per tenant lazily reloads the fingerprint-
            # verified artifact into the device catalog
            t1 = time.perf_counter()
            ok = 0
            first_query_secs = None
            for sid in sids:
                q0 = time.perf_counter()
                snap = c2.sql(sid, agg_sql)
                if first_query_secs is None:
                    first_query_secs = round(time.perf_counter() - q0, 4)
                if snap["status"] == "done" and "t" in c2.session(sid)[
                    "tables"
                ]:
                    ok += 1
            out["reload_all_tables_secs"] = round(
                time.perf_counter() - t1, 4
            )
            out["recovered_hot_tables"] = ok
            # ISSUE 11 phase split: journal-reload / cache-load from
            # startup, compile / dispatch from the first executed query
            cold = c2.status().get("cold_start", {})
            phases = dict(cold.get("phases", {}))
            fq = cold.get("first_query", {})
            out["time_to_first_query"] = {
                "total_secs": first_query_secs,
                "journal_reload_secs": phases.get("journal_reload_secs"),
                "cache_load_secs": phases.get("cache_load_secs"),
                "prewarmed_executables": phases.get(
                    "prewarmed_executables"
                ),
                "compile_secs": fq.get("compile_secs"),
                "dispatch_secs": fq.get("dispatch_secs"),
                "disk_load_secs": fq.get("disk_load_secs"),
                "xla_compiles": fq.get("xla_compiles"),
            }
        finally:
            d2.stop()
    return out


_COLD_START_SCRIPT = r"""
import json, os, sys, time
t_start = time.perf_counter()
import numpy as np
from fugue_tpu.column import col
from fugue_tpu.column import functions as ff
from fugue_tpu.execution import make_execution_engine
from fugue_tpu.execution.api import aggregate
from fugue_tpu.optimize import flush_persists
t_import = time.perf_counter()

src, out_path, cache_dir, batch_rows = sys.argv[1:5]
conf = {"fugue.jax.io.batch_rows": int(batch_rows)}
if cache_dir:
    conf["fugue.optimize.cache.dir"] = cache_dir
t0 = time.perf_counter()
e = make_execution_engine("jax", conf)
df = e.load_df(src, format_hint="parquet")
agg = aggregate(
    e.filter(df, col("k") < 96), partition_by="k",
    s=ff.sum(col("v")), c=ff.count(col("v")),
    engine=e, as_fugue=True,
)
e.save_df(agg, out_path, format_hint="parquet")
t1 = time.perf_counter()
flush_persists()
print(json.dumps({
    "import_secs": round(t_import - t_start, 4),
    "pipeline_secs": round(t1 - t0, 4),
    "process_secs": round(time.perf_counter() - t_start, 4),
    "compile_cache": e.compile_cache_stats,
    "exec_cache": e.exec_cache_stats,
}))
"""


def _config7_cold_start() -> Dict[str, Any]:
    """Cold-start scenario (ISSUE 11): the SAME pipeline end-to-end in
    FRESH OS processes — executable cache off, cache on with an empty
    dir (pays compile + persists), and cache on warm (the acceptance
    row: pipeline wall <1 s on this container with 0 XLA compiles,
    counter-verified). ``import_secs`` is reported separately: the
    interpreter + jax import cost is shared by every python process and
    not something the cache can (or should) hide."""
    import subprocess
    import sys as _sys

    import numpy as np
    import pandas as pd

    n = _scale(2_000_000)
    rng = np.random.default_rng(17)
    tmp = tempfile.mkdtemp(prefix="fugue_cold_")
    src = os.path.join(tmp, "src.parquet")
    pd.DataFrame(
        {
            "k": rng.integers(0, 128, n).astype(np.int32),
            "v": rng.random(n).astype(np.float32),
        }
    ).to_parquet(src)
    cache_dir = os.path.join(tmp, "xc")
    batch_rows = str(max(n // 16, 65_536))

    def run(tag: str, cache: str) -> Dict[str, Any]:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the bench process exports the legacy alias env var for the
        # headline's own cold/warm split: the controlled comparison here
        # must not let it leak into the cache-off variant
        env.pop("FUGUE_JAX_COMPILE_CACHE", None)
        out = subprocess.run(
            [
                _sys.executable, "-c", _COLD_START_SCRIPT,
                src, os.path.join(tmp, f"out_{tag}.parquet"),
                cache, batch_rows,
            ],
            capture_output=True, text=True, timeout=900, env=env,
        )
        if out.returncode != 0:  # surfaced in the artifact, not fatal
            return {"error": out.stderr[-1500:]}
        return json.loads(out.stdout.strip().splitlines()[-1])

    res: Dict[str, Any] = {"rows": n}
    res["cache_off"] = run("off", "")
    res["cache_on_cold"] = run("cold", cache_dir)  # compiles + persists
    res["cache_on_warm"] = run("warm", cache_dir)  # the fresh-process hit
    warm = res["cache_on_warm"]
    off = res["cache_off"]
    if "pipeline_secs" in warm and "pipeline_secs" in off:
        res["warm_vs_off_speedup"] = round(
            off["pipeline_secs"] / max(warm["pipeline_secs"], 1e-9), 2
        )
        res["warm_xla_compiles"] = warm["compile_cache"]["misses"]
        res["warm_under_1s"] = warm["pipeline_secs"] < 1.0
    return res


_SCALING_SCRIPT = r"""
import json, sys, time
n_dev, rows, jrows = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
import numpy as np
import pandas as pd
import jax
from fugue_tpu.column import col
from fugue_tpu.column import functions as ff
from fugue_tpu.collections.partition import PartitionSpec
from fugue_tpu.jax_backend import JaxExecutionEngine

assert len(jax.devices()) == n_dev, (len(jax.devices()), n_dev)
# shuffle pinned ON: this config measures the sharded relational path
# itself (auto would decline the small BENCH_SMALL shapes)
e = JaxExecutionEngine({"fugue.jax.shuffle": "on"})

def gb_frame(seed):
    # every frame carries EXACTLY the full 512-key domain (permuted):
    # num_segments is a static of the compiled program, so a randomly
    # missing key would read as a spurious recompile on the warm run
    r = np.random.default_rng(seed)
    return pd.DataFrame({
        "k": r.permutation(np.arange(rows, dtype=np.int64) % 512),
        "v": r.random(rows),
    })

aggs = [
    ff.sum(col("v")).alias("s"),
    ff.count(col("v")).alias("c"),
    ff.min(col("v")).alias("mn"),
]
spec = PartitionSpec(by=["k"])
# distinct pre-ingested frames per run: identical shapes share compiled
# programs, distinct data defeats any result memoization
gb = [e.to_df(gb_frame(s)) for s in (1, 2, 3)]
e.aggregate(gb[0], spec, aggs).as_array()  # compile + warm
m0 = e.compile_cache_stats["misses"]
best = float("inf")
for _ in range(3):  # best-of-6 damps the 1-core container's jitter
    for d in gb[1:]:
        t0 = time.perf_counter()
        e.aggregate(d, spec, aggs).as_array()
        best = min(best, time.perf_counter() - t0)
gb_rps = rows / best
gb_zero = e.compile_cache_stats["misses"] == m0
del gb  # release the group-by frames' device buffers before the join

jdom = max(jrows // 4, 64)

def j_frame(seed, n):
    # full key domain on both sides, same determinism rationale. The
    # domain keeps multiplicity low (right side: exactly 2 rows/key,
    # output ~2x left) so the timing measures the relational path, not
    # a many-to-many row explosion; 2 rows/key also keeps the right
    # side off the unique-right fast path so the sharded count program
    # actually runs
    r = np.random.default_rng(seed)
    return pd.DataFrame({
        "k": r.permutation(np.arange(n, dtype=np.int64) % jdom),
        "v": r.random(n),
    })

right = e.to_df(j_frame(9, jrows // 2).rename(columns={"v": "w"}))
lefts = [e.to_df(j_frame(s, jrows)) for s in (4, 5, 6)]
e.join(lefts[0], right, how="inner", on=["k"]).count()  # compile + warm
m1 = e.compile_cache_stats["misses"]
jbest = float("inf")
for _ in range(3):
    for d in lefts[1:]:
        t0 = time.perf_counter()
        e.join(d, right, how="inner", on=["k"]).count()
        jbest = min(jbest, time.perf_counter() - t0)
j_rps = jrows / jbest
j_zero = e.compile_cache_stats["misses"] == m1
print(json.dumps({
    "devices": n_dev,
    "groupby_rows_per_sec": round(gb_rps),
    "join_rows_per_sec": round(j_rps),
    "zero_recompile_warm": bool(gb_zero and j_zero),
    "shuffle_counts": e.shuffle_counts if n_dev > 1 else {},
}))
"""


def _config10_scaling() -> Dict[str, Any]:
    """Multi-device scaling curve (ISSUE 16): the SAME shuffle-on
    group-by and join workloads in fresh processes at devices=1/2/4/8
    (CPU via ``--xla_force_host_platform_device_count``), reporting
    rows/sec per point and ``parallel_efficiency`` per workload:
    ``(rps_n / rps_1) / min(n, cpu_cores)``. The min(n, cores)
    normalizer makes the number honest on this container: forced host
    devices beyond the physical core count cannot add real parallelism,
    so a point at n > cores measures shuffle OVERHEAD (efficiency ~1.0
    = the sharded path costs nothing extra), while n <= cores measures
    true scale-out. ``zero_recompile_warm`` asserts the one-trace
    invariant held at every device count."""
    import subprocess
    import sys as _sys

    rows = _scale(1_000_000)
    jrows = _scale(400_000)
    cores = os.cpu_count() or 1

    def run(n_dev: int) -> Dict[str, Any]:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        flags.append(f"--xla_force_host_platform_device_count={n_dev}")
        env["XLA_FLAGS"] = " ".join(flags)
        out = subprocess.run(
            [
                _sys.executable, "-c", _SCALING_SCRIPT,
                str(n_dev), str(rows), str(jrows),
            ],
            capture_output=True, text=True, timeout=900, env=env,
        )
        if out.returncode != 0:  # surfaced in the artifact, not fatal
            return {"devices": n_dev, "error": out.stderr[-1500:]}
        return json.loads(out.stdout.strip().splitlines()[-1])

    # TWO interleaved sweeps, merged per point by best rows/sec: the
    # points are measured in separate subprocesses minutes apart, and on
    # a small shared box the machine-state epochs between them swing
    # single measurements by tens of percent — a second decorrelated
    # pass damps exactly the noise that best-of-N inside one process
    # cannot see
    merged: Dict[int, Dict[str, Any]] = {}
    for _sweep in range(2):
        for n in (1, 2, 4, 8):
            p = run(n)
            prev = merged.get(n)
            if prev is None or "error" in prev:
                merged[n] = p
            elif "error" not in p:
                for k in ("groupby_rows_per_sec", "join_rows_per_sec"):
                    prev[k] = max(prev[k], p[k])
                prev["zero_recompile_warm"] = (
                    prev["zero_recompile_warm"] and p["zero_recompile_warm"]
                )
    points = [merged[n] for n in (1, 2, 4, 8)]
    res: Dict[str, Any] = {
        "rows": rows,
        "join_rows": jrows,
        "cpu_cores": cores,
        "points": points,
        "efficiency_normalizer": "min(devices, cpu_cores)",
    }
    base = points[0]
    eff: Dict[str, Dict[str, float]] = {}
    if "error" not in base:
        for p in points[1:]:
            if "error" in p:
                continue
            n = p["devices"]
            denom = float(min(n, cores))
            eff[str(n)] = {
                "groupby": round(
                    p["groupby_rows_per_sec"]
                    / max(base["groupby_rows_per_sec"], 1)
                    / denom,
                    3,
                ),
                "join": round(
                    p["join_rows_per_sec"]
                    / max(base["join_rows_per_sec"], 1)
                    / denom,
                    3,
                ),
            }
    res["parallel_efficiency"] = eff
    res["zero_recompile_warm"] = all(
        p.get("zero_recompile_warm", False)
        for p in points
        if "error" not in p
    )
    return res


def _config8_serving_fleet() -> Dict[str, Any]:
    """Fleet serving scenario (ISSUE 13): aggregate qps + p99 through
    the front-tier router at replicas=1 and replicas=2 (each replica
    owns its own engine; both caches off so the numbers measure serving
    EXECUTION, comparable with config 6), plus a rolling restart of the
    2-replica fleet under a continuous client loop — reporting
    failed_calls (the zero-drop contract) and migration_secs (the
    journal-adoption handoff cost)."""
    import tempfile
    import threading as _threading

    import numpy as np
    import pandas as pd

    from fugue_tpu.serve import ServeClient, ServeFleet

    clients = 4
    queries_per_client = 6
    rows = _scale(200_000)
    agg_sql = "SELECT k, SUM(v) AS s, COUNT(*) AS c FROM t GROUP BY k"
    out: Dict[str, Any] = {
        "clients": clients,
        "queries_per_client": queries_per_client,
        "rows_per_table": rows,
        # this block measures the default FIFO queue; config 12 runs the
        # predictive scheduler, so the fleet rows stay comparable
        "scheduler": "fifo",
    }

    def _fleet_conf(tmp: str) -> Dict[str, Any]:
        return {
            "fugue.serve.state_path": tmp + "/state",
            "fugue.serve.max_concurrent": clients,
            "fugue.serve.breaker.threshold": 0,
            # execution, not cache reads: both result tiers off
            "fugue.serve.result_cache": False,
            "fugue.serve.fleet.result_cache_dir": "",
            "fugue.serve.fleet.health_interval": 0.1,
            "fugue.serve.drain_timeout": 30.0,
        }

    def _setup_tenants(fleet: Any) -> list:
        rng = np.random.default_rng(13)
        handles = []
        for _ in range(clients):
            c = ServeClient([fleet.address], retries=10, timeout=600)
            sid = c.create_session()
            pdf = pd.DataFrame(
                {
                    "k": rng.integers(0, 64, rows).astype(np.int64),
                    "v": rng.random(rows),
                }
            )
            # hot-table setup + program warmup, UNMEASURED (config 6
            # idiom): saved once via the owning replica's engine, then
            # queried repeatedly through the router
            rid = fleet.router.affinity()[sid]
            daemon = fleet.replica(rid)
            daemon.sessions.get(sid).save_table(
                "t", daemon.engine.to_df(pdf)
            )
            c.sql(sid, agg_sql)  # warm the compiled programs
            handles.append((c, sid))
        return handles

    def _qps_block(n_replicas: int) -> Dict[str, Any]:
        tmp = tempfile.mkdtemp(prefix="fugue_fleet_bench_")
        res: Dict[str, Any] = {"replicas": n_replicas}
        latencies: list = []
        errors: list = []
        lat_lock = _threading.Lock()
        with ServeFleet(_fleet_conf(tmp), replicas=n_replicas) as fleet:
            handles = _setup_tenants(fleet)

            def one_client(c: Any, sid: str) -> None:
                try:
                    mine = []
                    for _ in range(queries_per_client):
                        t0 = time.perf_counter()
                        r = c.sql(sid, agg_sql)
                        mine.append((time.perf_counter() - t0) * 1000.0)
                        if r["status"] != "done":
                            errors.append(r.get("error"))
                    with lat_lock:
                        latencies.extend(mine)
                except Exception as ex:  # pragma: no cover - in json
                    errors.append(repr(ex))

            threads = [
                _threading.Thread(target=one_client, args=h)
                for h in handles
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            res["sessions_per_replica"] = fleet.router.describe()[
                "sessions_per_replica"
            ]
        total = clients * queries_per_client
        res["errors"] = errors
        res["queries"] = total
        res["wall_secs"] = round(wall, 4)
        res["queries_per_sec"] = (
            round(total / wall, 2) if wall > 0 else 0.0
        )
        if latencies:
            res["p50_ms"] = round(float(np.percentile(latencies, 50)), 2)
            res["p99_ms"] = round(float(np.percentile(latencies, 99)), 2)
        return res

    def _rolling_restart_block() -> Dict[str, Any]:
        tmp = tempfile.mkdtemp(prefix="fugue_fleet_bench_rr_")
        res: Dict[str, Any] = {"replicas": 2}
        stop = _threading.Event()
        failed: list = []
        completed: list = []
        with ServeFleet(_fleet_conf(tmp), replicas=2) as fleet:
            handles = _setup_tenants(fleet)

            def loop(c: Any, sid: str) -> None:
                while not stop.is_set():
                    try:
                        r = c.sql(sid, agg_sql)
                        (completed if r["status"] == "done" else failed
                         ).append(sid)
                    except Exception as ex:  # pragma: no cover
                        failed.append(repr(ex))
                    time.sleep(0.01)

            threads = [
                _threading.Thread(target=loop, args=h) for h in handles
            ]
            for t in threads:
                t.start()
            time.sleep(0.5)  # continuous load established
            stats = fleet.rolling_restart()
            time.sleep(0.5)  # ...and keeps flowing on the fresh fleet
            stop.set()
            for t in threads:
                t.join(timeout=60)
        res["failed_calls"] = len(failed)
        res["completed_calls"] = len(completed)
        res["migrated_sessions"] = stats["migrated_sessions"]
        res["migration_secs"] = stats["migration_secs"]
        res["restart_secs"] = stats["secs"]
        return res

    out["replicas_1"] = _qps_block(1)
    out["replicas_2"] = _qps_block(2)
    out["rolling_restart"] = _rolling_restart_block()
    return out


def _config9_continuous() -> Dict[str, Any]:
    """Continuous execution (ISSUE 15): a standing pipeline tails
    arriving parquet files and maintains a serve session table as a
    materialized view. Reports sustained micro-batch throughput
    (fold rows/sec across the waves), end-to-end freshness latency
    (file LANDS on storage -> refreshed view QUERYABLE over HTTP with
    the new data), the zero-recompile counter contract (one XLA trace
    total across all micro-batches), and exact parity of the final view
    with the one-shot batch aggregate over the full file union."""
    import os as _os
    import tempfile

    import numpy as np
    import pandas as pd
    import pyarrow as _pa
    import pyarrow.parquet as _pq

    from fugue_tpu.serve import ServeClient, ServeDaemon

    waves = 5
    rows_per_wave = _scale(80_000)
    tmp = tempfile.mkdtemp(prefix="fugue_stream_bench_")
    src = _os.path.join(tmp, "in")
    _os.makedirs(src)
    rng = np.random.default_rng(15)
    out: Dict[str, Any] = {
        "waves": waves,
        "rows_per_wave": rows_per_wave,
    }

    def land(i: int) -> pd.DataFrame:
        pdf = pd.DataFrame(
            {
                "k": rng.integers(0, 64, rows_per_wave).astype(np.int64),
                "v": rng.random(rows_per_wave),
            }
        )
        t = _os.path.join(src, f".w{i}.tmp")
        _pq.write_table(_pa.Table.from_pandas(pdf, preserve_index=False), t)
        _os.replace(t, _os.path.join(src, f"w{i}.parquet"))
        return pdf

    conf = {
        "fugue.serve.state_path": tmp + "/state",
        "fugue.serve.breaker.threshold": 0,
    }
    q = "SELECT k, s, c FROM sess ORDER BY k LIMIT 100"
    frames = []
    fold_secs = 0.0
    freshness: list = []
    with ServeDaemon(conf) as daemon:
        c = ServeClient(*daemon.address, timeout=600)
        sid = c.create_session()
        # wave 0 rides the registration step (compile + first fold,
        # reported separately as the cold share)
        frames.append(land(0))
        t0 = time.perf_counter()
        rep = c.register_pipeline(
            sid,
            {
                "name": "sess",
                "source": src,
                "keys": ["k"],
                "aggs": [["s", "sum", "v"], ["c", "count", "v"]],
                # one uniform host chunk per wave: every fold shares one
                # padded row bucket, so the zero-recompile counter
                # contract is measurable (pyarrow's default batching
                # would tail each file with a ragged second shape)
                "batch_rows": rows_per_wave,
            },
        )["report"]
        c.sql(sid, q)  # view queryable; warms the query programs too
        out["first_batch_secs"] = round(time.perf_counter() - t0, 4)
        for i in range(1, waves):
            frames.append(land(i))
            t_land = time.perf_counter()
            rep = c.step_pipeline(sid, "sess")
            r = c.sql(sid, q)
            freshness.append(time.perf_counter() - t_land)
            fold_secs += rep["secs"]
            assert rep["files"] == 1 and rep["refreshed"], rep
        snap = c.pipeline(sid, "sess")
        agg_stats = snap["aggregator"]
        # exact parity with the one-shot batch run over the file union
        exp = (
            pd.concat(frames).groupby("k")["v"]
            .agg(["sum", "count"]).reset_index()
        )
        got = pd.DataFrame(r["result"]["rows"], columns=["k", "s", "c"])
        parity = bool(
            np.allclose(got["s"].to_numpy(), exp["sum"].to_numpy())
            and (got["c"].to_numpy() == exp["count"].to_numpy()).all()
        )
    warm_rows = rows_per_wave * (waves - 1)
    out["micro_batches"] = snap["progress"]["batches"]
    out["rows_total"] = agg_stats["rows"]
    out["fold_rows_per_sec"] = (
        round(warm_rows / fold_secs, 1) if fold_secs > 0 else 0.0
    )
    out["freshness_secs"] = {
        "p50": round(float(np.percentile(freshness, 50)), 4),
        "max": round(float(np.max(freshness)), 4),
    }
    out["xla_traces"] = agg_stats["traces"]
    out["zero_recompiles_after_first_batch"] = agg_stats["traces"] == 1
    out["batch_parity"] = parity
    return out


def _config11_lake() -> Dict[str, Any]:
    """Versioned table storage (ISSUE 17): optimistic-CAS commit
    throughput under k concurrent writers (with the conflict-retry rate
    the jittered backoff produces), the manifest-stats file-prune ratio
    of a selective scan vs the footer-only baseline (every file opened),
    and the scan speedup compaction buys on a many-small-files table."""
    import tempfile
    import threading

    import numpy as np
    import pandas as pd
    import pyarrow as _pa

    from fugue_tpu.lake import LakeTable

    tmp = tempfile.mkdtemp(prefix="fugue_lake_bench_")
    conf = {"fugue.lake.commit.backoff": 0.002,
            "fugue.lake.commit.retries": 200}
    out: Dict[str, Any] = {}

    # -- commit throughput under k racing writers --------------------------
    k_writers, per_writer = 4, 8
    rows_per_commit = _scale(20_000)
    rng = np.random.default_rng(17)

    def batch(w: int, b: int) -> _pa.Table:
        return _pa.Table.from_pandas(
            pd.DataFrame(
                {
                    "w": np.full(rows_per_commit, w, dtype=np.int64),
                    "t": np.arange(rows_per_commit, dtype=np.int64)
                    + b * rows_per_commit,
                    "v": rng.random(rows_per_commit),
                }
            ),
            preserve_index=False,
        )

    tables = [LakeTable(tmp + "/commits", conf=conf)
              for _ in range(k_writers)]

    def writer(i: int) -> None:
        for b in range(per_writer):
            tables[i].append(batch(i, b))

    threads = [
        threading.Thread(target=writer, args=(i,)) for i in range(k_writers)
    ]
    t0 = time.perf_counter()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    commit_secs = time.perf_counter() - t0
    commits = sum(t.counters["commits"] for t in tables)
    conflicts = sum(t.counters["conflicts"] for t in tables)
    head = LakeTable(tmp + "/commits")
    assert head.current_version() == k_writers * per_writer
    assert head.read_manifest(head.current_version()).rows == (
        k_writers * per_writer * rows_per_commit
    )
    out["commit"] = {
        "writers": k_writers,
        "commits": commits,
        "commits_per_sec": round(commits / commit_secs, 2),
        "conflict_retries": conflicts,
        "conflict_retry_rate": round(conflicts / commits, 3),
    }

    # -- manifest-stats file pruning vs footer-only ------------------------
    # files are range-partitioned on t by construction (each commit owns
    # a distinct t window), so a selective window predicate can prune
    # whole files from the manifest without touching a parquet footer
    lo = (per_writer - 1) * rows_per_commit  # only the LAST window
    triples = [["t", ">=", lo]]
    probe = LakeTable(tmp + "/commits")
    probe.scan(pruning=triples)  # ONE scan: per-scan prune counters
    scan_t = _timed(lambda: head.scan(pruning=triples), warm=1)
    footer = LakeTable(tmp + "/commits")
    full_t = _timed(lambda: footer.scan(), warm=1)
    total_files = len(head.read_manifest(head.current_version()).files)
    out["pruning"] = {
        "files_total": total_files,
        "files_pruned": probe.counters["files_pruned"],
        "prune_ratio": round(
            probe.counters["files_pruned"] / total_files, 3
        ),
        "pruned_scan_secs": round(scan_t, 4),
        "footer_only_scan_secs": round(full_t, 4),
        "speedup": round(full_t / scan_t, 2) if scan_t > 0 else 0.0,
    }

    # -- compaction scan speedup -------------------------------------------
    frag = LakeTable(tmp + "/frag", conf=conf)
    small_files, small_rows = 64, _scale(10_000) // 8
    for i in range(small_files):
        frag.append(
            _pa.table({"k": np.full(small_rows, i, dtype=np.int64),
                       "v": rng.random(small_rows)})
        )
    before = _timed(lambda: LakeTable(tmp + "/frag").scan(), warm=1)
    m = frag.compact(target_rows=small_files * small_rows)
    after = _timed(lambda: LakeTable(tmp + "/frag").scan(), warm=1)
    out["compaction"] = {
        "files_before": small_files,
        "files_after": len(m.files),
        "scan_secs_before": round(before, 4),
        "scan_secs_after": round(after, 4),
        "speedup": round(before / after, 2) if after > 0 else 0.0,
    }
    return out


def _config12_overload() -> Dict[str, Any]:
    """Overload survival (ISSUE 18): a heavy-tailed query mix (90%
    cheap / 10% heavy, a priority submission every 10th) offered through
    a diurnal arrival ramp at 1x and then 2x worker count, against the
    PREDICTIVE scheduler. The 2x phase runs with an admission wait
    budget derived from the 1x calibration (3x its p99 — the acceptance
    bound itself), so overload SHEDS low-priority arrivals with a
    drain-sized Retry-After instead of letting accepted latency grow
    without bound. Reports p50/p99 of ACCEPTED work at both rates, the
    shed vs lost split (accepted work must NEVER be lost: lost == 0 at
    both rates), the continuous plane riding through the storm
    (standing-pipeline folds and lake CAS commits, all landed), and an
    autoscale up->down cycle with a HARD KILL at the ``serve.scale``
    fault site (zero session loss)."""
    import math
    import os as _os
    import tempfile
    import threading as _threading

    import numpy as np
    import pandas as pd
    import pyarrow as _pa
    import pyarrow.parquet as _pq

    from fugue_tpu.lake import LakeTable
    from fugue_tpu.serve import (
        ServeAPIError,
        ServeClient,
        ServeDaemon,
        ServeFleet,
    )
    from fugue_tpu.testing.faults import FaultPlan, FaultSpec, inject_faults

    sessions = 4
    queries_per_worker = 12
    rows = _scale(200_000)
    cheap_sql = "SELECT k, SUM(v) AS s FROM t GROUP BY k"
    heavy_sql = (
        "SELECT k, SUM(v) AS s, COUNT(*) AS c, MAX(v) AS hi, "
        "MIN(v) AS lo, AVG(v) AS av FROM t GROUP BY k"
    )
    out: Dict[str, Any] = {
        "scheduler": "predictive",
        "sessions": sessions,
        "queries_per_worker": queries_per_worker,
        "rows_per_table": rows,
        "mix": {"heavy_fraction": 0.1, "priority_every": 10},
    }

    def _daemon_conf(tmp: str, max_wait: float) -> Dict[str, Any]:
        return {
            "fugue.serve.scheduler": "predictive",
            "fugue.serve.state_path": tmp + "/state",
            "fugue.serve.max_concurrent": sessions,
            "fugue.serve.breaker.threshold": 0,
            # execution, not cache reads (config 6 idiom): a result hit
            # would collapse the repeated mix into no load at all
            "fugue.serve.result_cache": False,
            "fugue.serve.admission.max_predicted_wait": max_wait,
        }

    def _offered_phase(
        workers_per_session: int, max_wait: float
    ) -> Dict[str, Any]:
        tmp = tempfile.mkdtemp(prefix="fugue_overload_bench_")
        latencies: list = []
        shed: list = []
        lost: list = []
        errors: list = []
        lock = _threading.Lock()
        with ServeDaemon(_daemon_conf(tmp, max_wait)) as daemon:
            host, port = daemon.address
            rng = np.random.default_rng(18)
            handles = []
            for _ in range(sessions):
                # shed must SURFACE (503 + Retry-After), not vanish into
                # the client's transparent retry loop: retries=0
                c = ServeClient(host, port, retries=0, timeout=600)
                sid = c.create_session()
                pdf = pd.DataFrame(
                    {
                        "k": rng.integers(0, 64, rows).astype(np.int64),
                        "v": rng.random(rows),
                    }
                )
                daemon.sessions.get(sid).save_table(
                    "t", daemon.engine.to_df(pdf)
                )
                # warm BOTH tails' programs and seed the cost history
                c.sql(sid, cheap_sql)
                c.sql(sid, heavy_sql)
                handles.append((c, sid))

            def worker(c: Any, sid: str, seed: int) -> None:
                wrng = np.random.default_rng(seed)
                mine = []
                for i in range(queries_per_worker):
                    # diurnal ramp: quiet -> peak (no gap) -> quiet
                    time.sleep(
                        0.04
                        * (1 + math.cos(2 * math.pi * i / queries_per_worker))
                        / 2
                    )
                    sql = (
                        heavy_sql if wrng.random() < 0.1 else cheap_sql
                    )
                    prio = 100 if i % 10 == 0 else 0
                    t0 = time.perf_counter()
                    try:
                        jid = c.submit_async(
                            sid, sql, priority=prio, collect=False
                        )
                    except ServeAPIError as ex:
                        if ex.status == 503:
                            with lock:
                                shed.append(sid)
                            continue
                        with lock:
                            errors.append(repr(ex))
                        continue
                    # accepted work is COMMITTED: it must complete
                    try:
                        r = c.wait(jid)
                        mine.append((time.perf_counter() - t0) * 1000.0)
                        if r["status"] != "done":
                            with lock:
                                lost.append(r.get("error"))
                    except Exception as ex:  # pragma: no cover - in json
                        with lock:
                            lost.append(repr(ex))
                with lock:
                    latencies.extend(mine)

            threads = [
                _threading.Thread(target=worker, args=(c, sid, 100 + j))
                for j, (c, sid) in enumerate(handles)
                for _ in range(workers_per_session)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            rej = daemon.status()["backpressure"]["rejections"]
        offered = sessions * workers_per_session * queries_per_worker
        res: Dict[str, Any] = {
            "workers": sessions * workers_per_session,
            "offered": offered,
            "accepted": len(latencies),
            "shed": len(shed),
            "shed_counted_by_daemon": rej.get("shed", 0),
            "lost": len(lost),
            "errors": errors,
            "wall_secs": round(wall, 4),
            "wait_budget_secs": max_wait,
        }
        if latencies:
            res["p50_ms"] = round(float(np.percentile(latencies, 50)), 2)
            res["p99_ms"] = round(float(np.percentile(latencies, 99)), 2)
        return res

    # 1x calibration: one worker per session, an effectively-unbounded
    # wait budget — nothing sheds, p99 is the baseline
    rate_1x = _offered_phase(1, 600.0)
    out["rate_1x"] = rate_1x
    p99_1x_secs = rate_1x.get("p99_ms", 1000.0) / 1000.0
    # 2x overload: double the workers, and bound accepted wait at 3x the
    # calibrated p99 (the acceptance bound) so excess arrivals shed
    budget = max(0.1, round(3.0 * p99_1x_secs, 3))
    rate_2x = _offered_phase(2, budget)
    out["rate_2x"] = rate_2x
    if "p99_ms" in rate_1x and "p99_ms" in rate_2x:
        ratio = rate_2x["p99_ms"] / max(rate_1x["p99_ms"], 1e-9)
        out["p99_ratio_2x_over_1x"] = round(ratio, 2)
        out["accepted_p99_within_3x"] = bool(ratio <= 3.0)
    out["zero_accepted_lost"] = (
        rate_1x["lost"] == 0 and rate_2x["lost"] == 0
    )

    # -- the continuous plane through the storm ----------------------------
    # a standing pipeline folding waves and a lake table taking CAS
    # commits while a 2x burst saturates the SAME process: overload may
    # shed interactive arrivals, but committed continuous work lands
    def _continuous_block() -> Dict[str, Any]:
        tmp = tempfile.mkdtemp(prefix="fugue_overload_cont_")
        src = _os.path.join(tmp, "in")
        _os.makedirs(src)
        rng = np.random.default_rng(19)
        waves = 6
        rows_per_wave = _scale(20_000)

        def land(i: int) -> None:
            pdf = pd.DataFrame(
                {
                    "k": rng.integers(0, 8, rows_per_wave).astype(np.int64),
                    "v": rng.random(rows_per_wave),
                }
            )
            t = _os.path.join(src, f".w{i}.tmp")
            _pq.write_table(
                _pa.Table.from_pandas(pdf, preserve_index=False), t
            )
            _os.replace(t, _os.path.join(src, f"w{i}.parquet"))

        lake = LakeTable(tmp + "/lake", conf={
            "fugue.lake.commit.backoff": 0.002,
            "fugue.lake.commit.retries": 200,
        })
        commits_tried = 0
        with ServeDaemon(_daemon_conf(tmp, 0.5)) as daemon:
            host, port = daemon.address
            c = ServeClient(host, port, retries=0, timeout=600)
            sid = c.create_session()
            pdf = pd.DataFrame(
                {
                    "k": rng.integers(0, 64, rows).astype(np.int64),
                    "v": rng.random(rows),
                }
            )
            daemon.sessions.get(sid).save_table(
                "t", daemon.engine.to_df(pdf)
            )
            c.sql(sid, cheap_sql)
            land(0)
            c.register_pipeline(
                sid,
                {
                    "name": "sess",
                    "source": src,
                    "keys": ["k"],
                    "aggs": [["s", "sum", "v"], ["c", "count", "v"]],
                    "batch_rows": rows_per_wave,
                },
            )
            shed_local: list = []
            stop = _threading.Event()

            def storm() -> None:
                while not stop.is_set():
                    try:
                        jid = c.submit_async(sid, cheap_sql, collect=False)
                        c.wait(jid)
                    except ServeAPIError as ex:
                        if ex.status != 503:
                            raise
                        shed_local.append(1)
                        time.sleep(0.01)

            stormers = [
                _threading.Thread(target=storm) for _ in range(sessions)
            ]
            for t in stormers:
                t.start()
            fold_errors: list = []
            try:
                for i in range(1, waves):
                    land(i)
                    rep = c.step_pipeline(sid, "sess")
                    if not (rep["files"] == 1 and rep["refreshed"]):
                        fold_errors.append(rep)
                    commits_tried += 1
                    lake.append(
                        _pa.table(
                            {
                                "w": np.full(1000, i, dtype=np.int64),
                                "v": rng.random(1000),
                            }
                        )
                    )
            finally:
                stop.set()
                for t in stormers:
                    t.join(timeout=60)
            snap = c.pipeline(sid, "sess")
        folds = snap["progress"]["batches"]
        return {
            "waves_landed": waves,
            "pipeline_folds": folds,
            "folds_lost": waves - folds,
            "fold_errors": fold_errors,
            "lake_commits": lake.counters["commits"],
            "commits_lost": commits_tried - lake.current_version(),
            "interactive_shed_during_storm": len(shed_local),
        }

    out["continuous_through_storm"] = _continuous_block()

    # -- autoscale cycle with a hard kill at serve.scale -------------------
    def _autoscale_block() -> Dict[str, Any]:
        tmp = tempfile.mkdtemp(prefix="fugue_overload_scale_")
        conf = {
            "fugue.serve.state_path": tmp + "/state",
            "fugue.serve.max_concurrent": 1,
            "fugue.serve.breaker.threshold": 0,
            "fugue.serve.result_cache": False,
            "fugue.serve.fleet.health_interval": 0.05,
            "fugue.serve.fleet.death_threshold": 1,
            # the controller thread is parked (interval=60): the bench
            # drives tick() deterministically, like the chaos tests
            "fugue.serve.autoscale.max_replicas": 2,
            "fugue.serve.autoscale.interval": 60.0,
            "fugue.serve.autoscale.scale_up_queue": 1,
            "fugue.serve.autoscale.sustain_ticks": 1,
            "fugue.serve.autoscale.idle_ticks": 1,
            "fugue.serve.autoscale.cooldown": 0.0,
        }
        res: Dict[str, Any] = {}
        with ServeFleet(conf, replicas=1) as fleet:
            scaler = fleet.autoscaler
            c = ServeClient([fleet.address], retries=10, timeout=600)
            sid0 = c.create_session()
            c.sql(
                sid0,
                "CREATE [[0,1],[0,2],[1,3]] SCHEMA k:long,v:long",
                save_as="t",
                collect=False,
            )
            agg = "SELECT k, SUM(v) AS s FROM t GROUP BY k"
            c.sql(sid0, agg)
            # pressure: async bursts until a tick catches the queue deep
            # enough to add hardware
            t0 = time.perf_counter()
            jids: list = []
            decision = ""
            for _ in range(40):
                jids.extend(
                    c.submit_async(sid0, agg, collect=False)
                    for _ in range(8)
                )
                decision = scaler.tick()
                if decision.startswith("scale_up"):
                    break
            res["scaled_up"] = decision.startswith("scale_up")
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if fleet.router.check_health().get("r1") == "healthy":
                    break
                time.sleep(0.05)
            res["scale_up_secs"] = round(time.perf_counter() - t0, 4)
            for jid in jids:
                c.wait(jid)
            # a fresh session lands on the new replica, then a HARD KILL
            # mid-scale-down degrades to an ordinary death failover
            sid1 = c.create_session()
            c.sql(
                sid1,
                "CREATE [[0,1],[0,2],[1,3]] SCHEMA k:long,v:long",
                save_as="t",
                collect=False,
            )
            victim_rid = fleet.router.affinity()[sid1]
            res["victim_replica"] = victim_rid
            plan = FaultPlan(
                FaultSpec(
                    "serve.scale", f"down {victim_rid}", times=1,
                    error=lambda: OSError("injected kill mid-scale-down"),
                ),
                seed=12,
            )
            t0 = time.perf_counter()
            try:
                with inject_faults(plan):
                    fleet.retire_replica(victim_rid)
                res["hard_kill_injected"] = False
            except OSError:
                res["hard_kill_injected"] = True
            survivor = next(
                r for r in fleet.replica_ids if r != victim_rid
            )
            deadline = time.monotonic() + 30
            adopted = False
            while time.monotonic() < deadline:
                if fleet.router.affinity().get(sid1) == survivor:
                    adopted = True
                    break
                time.sleep(0.05)
            res["adoption_secs"] = round(time.perf_counter() - t0, 4)
            r = c.sql(sid1, agg)
            res["sessions_lost"] = 0 if (
                adopted
                and r["status"] == "done"
                and sorted(r["result"]["rows"]) == [[0, 3], [1, 3]]
            ) else 1
            # the retry of the retire completes the cycle cleanly
            fleet.retire_replica(victim_rid)
            res["replicas_after_cycle"] = len(fleet.replica_ids)
            d = scaler.describe()
            res["scale_ups"] = d["scale_ups"]
        return res

    out["autoscale_cycle"] = _autoscale_block()
    return out


_DEVICE_LOSS_SCRIPT = r"""
import json, sys, time
rows = int(sys.argv[1])
import numpy as np
import pandas as pd
import jax

assert len(jax.devices()) == 4, jax.devices()
from fugue_tpu.column import col
from fugue_tpu.column import functions as ff
from fugue_tpu.jax_backend import JaxExecutionEngine
from fugue_tpu.testing.faults import (
    FaultPlan, FaultSpec, device_lost, inject_faults,
)
from fugue_tpu.workflow import FugueWorkflow

CONF = {
    "fugue.workflow.retry.max_attempts": 3,
    "fugue.workflow.retry.backoff": 0.0,
    "fugue.workflow.retry.jitter": 0.0,
}
rng = np.random.default_rng(13)
left = pd.DataFrame({
    "k": rng.integers(0, 128, rows).astype(np.int64),
    "v": rng.random(rows),
})
right = pd.DataFrame({
    "k": rng.integers(0, 128, rows // 4).astype(np.int64),
    "w": rng.integers(0, 100, rows // 4).astype(np.int64),
})

def build():
    dag = FugueWorkflow()
    j = dag.df(left).inner_join(dag.df(right), on=["k"])
    j.partition_by("k").aggregate(
        total=ff.sum(col("v")), mx=ff.max(col("w"))
    ).yield_dataframe_as("res", as_local=True)
    return dag

def rows_of(res):
    return sorted(
        tuple(round(x, 9) if isinstance(x, float) else x for x in r)
        for r in res["res"].as_array()
    )

e0 = JaxExecutionEngine(dict(CONF))
build().run(e0)  # compile warm-up: the chaos delta measures recovery
t0 = time.perf_counter()
expected = rows_of(build().run(e0))
baseline = time.perf_counter() - t0
e0.stop()

e = JaxExecutionEngine(dict(CONF))
build().run(e)
# time-to-recovery = the degraded-mesh rebuild window itself (retire
# pools, remake mesh, evacuate/re-materialize live frames), measured
# around the engine's recovery hook
rec = {"secs": 0.0}
_real = e.recover_from_device_loss
def timed(ex):
    r0 = time.perf_counter()
    ok = _real(ex)
    rec["secs"] += time.perf_counter() - r0
    return ok
e.recover_from_device_loss = timed
plan = FaultPlan(
    FaultSpec("task", "RunJoin*", times=1, error=lambda: device_lost(1)),
    seed=13,
)
t0 = time.perf_counter()
with inject_faults(plan):
    res = build().run(e)
chaos = time.perf_counter() - t0
got = rows_of(res)
t0 = time.perf_counter()
degraded_again = rows_of(build().run(e)) == expected
degraded_secs = time.perf_counter() - t0
print(json.dumps({
    "devices": 4,
    "rows": rows,
    "baseline_secs": round(baseline, 4),
    "chaos_secs": round(chaos, 4),
    "time_to_recovery_secs": round(rec["secs"], 4),
    "device_recoveries": int(e.device_recoveries),
    "survivors": int(e.surviving_device_count),
    # exact aggregate parity through the loss AND on the degraded
    # 3-device mesh afterwards = zero lost committed work
    "zero_lost_committed_work": bool(got == expected and degraded_again),
    "degraded_followup_secs": round(degraded_secs, 4),
}))
e.stop()
"""


_DEVICE_LOSS_FLEET_SCRIPT = r"""
import json, tempfile, time
import jax

assert len(jax.devices()) == 4, jax.devices()
from fugue_tpu.serve import ServeClient, ServeFleet
from fugue_tpu.testing.faults import device_lost

tmp = tempfile.mkdtemp(prefix="fugue_device_loss_fleet_")
conf = {
    "fugue.serve.state_path": tmp + "/state",
    "fugue.serve.max_concurrent": 2,
    "fugue.serve.breaker.threshold": 0,
    "fugue.serve.result_cache": False,
    "fugue.serve.fleet.health_interval": 0.05,
    "fugue.serve.fleet.death_threshold": 1,
    # parked controller (interval=60): tick() driven deterministically
    "fugue.serve.autoscale.max_replicas": 2,
    "fugue.serve.autoscale.interval": 60.0,
    "fugue.serve.autoscale.scale_up_queue": 2,
    "fugue.serve.autoscale.sustain_ticks": 2,
    "fugue.serve.autoscale.idle_ticks": 2,
    "fugue.serve.autoscale.cooldown": 0.0,
}
agg = "SELECT k, SUM(v) AS s FROM t GROUP BY k"
out = {}
with ServeFleet(conf, replicas=1) as fleet:
    scaler = fleet.autoscaler
    c = ServeClient([fleet.address], retries=10, timeout=600)
    sid = c.create_session()
    c.sql(
        sid, "CREATE [[0,1],[0,2],[1,3]] SCHEMA k:long,v:long",
        save_as="t", collect=False,
    )
    # a device dies under r0: its engine rebuilds onto the survivors
    # and /v1/health flips to "degraded"
    t0 = time.perf_counter()
    assert fleet.replica("r0")._engine.recover_from_device_loss(
        device_lost(2)
    )
    out["recover_secs"] = round(time.perf_counter() - t0, 4)
    # degraded = sustained pressure: first tick spawns the healthy
    # replacement, next tick drain-retires the reduced-mesh replica
    t0 = time.perf_counter()
    d1 = scaler.tick()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if fleet.router.check_health().get("r1") == "healthy":
            break
        time.sleep(0.05)
    out["replace_secs"] = round(time.perf_counter() - t0, 4)
    t0 = time.perf_counter()
    d2 = scaler.tick()
    out["retire_secs"] = round(time.perf_counter() - t0, 4)
    out["decisions"] = [d1, d2]
    r = c.sql(sid, agg)
    out["sessions_lost"] = 0 if (
        fleet.router.affinity().get(sid) == "r1"
        and r["status"] == "done"
        and sorted(r["result"]["rows"]) == [[0, 3], [1, 3]]
        and "t" in c.session(sid)["tables"]
    ) else 1
    out["replicas_after"] = list(fleet.replica_ids)
print(json.dumps(out))
"""


def _config13_device_loss() -> Dict[str, Any]:
    """Device-fault resilience (ISSUE 19): a fresh 4-device process
    loses one device mid shuffle-join (seeded chaos at the ``task``
    site) and the query completes on the 3 survivors with exact
    aggregate parity — reporting ``time_to_recovery_secs`` (the
    degraded-mesh rebuild window), the chaos-vs-baseline wall-clock
    delta, and ``zero_lost_committed_work``. The fleet leg degrades a
    replica's engine the same way and measures the autoscaler's
    replace-then-retire cycle (spawn healthy, drain-retire degraded)
    with ``sessions_lost == 0``."""
    import subprocess
    import sys as _sys

    rows = _scale(200_000)

    def run(script: str, args: list) -> Dict[str, Any]:
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        flags = [
            f
            for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")
        ]
        flags.append("--xla_force_host_platform_device_count=4")
        env["XLA_FLAGS"] = " ".join(flags)
        out = subprocess.run(
            [_sys.executable, "-c", script] + args,
            capture_output=True, text=True, timeout=900, env=env,
        )
        if out.returncode != 0:  # surfaced in the artifact, not fatal
            return {"error": out.stderr[-1500:]}
        return json.loads(out.stdout.strip().splitlines()[-1])

    return {
        "query_recovery": run(_DEVICE_LOSS_SCRIPT, [str(rows)]),
        "fleet_failover": run(_DEVICE_LOSS_FLEET_SCRIPT, []),
    }


def _bench() -> Dict[str, Any]:
    headline = _bench_headline()
    configs = {
        "1_map_letter_to_food": _config1_map_letter_to_food(),
        "2_partition_udf": _config2_partition_udf(),
        "3_fuguesql_groupby": _config3_fuguesql_groupby(),
        "3b_sql_join": _config3b_sql_join(),
        "4_cotransform": _config4_cotransform(),
        "5_e2e_parquet": _config5_e2e_parquet(),
        "6_serving_daemon": _config6_serving_daemon(),
        "7_cold_start": _config7_cold_start(),
        "8_serving_fleet": _config8_serving_fleet(),
        "9_continuous": _config9_continuous(),
        "10_scaling": _config10_scaling(),
        "11_lake": _config11_lake(),
        "12_overload": _config12_overload(),
        "13_device_loss": _config13_device_loss(),
    }
    headline["detail"]["configs"] = configs
    # the scaling curve's summary rides the headline contract: devices
    # is already in detail (the headline engine's mesh), the measured
    # multi-device efficiency joins it here
    scaling = configs["10_scaling"]
    headline["detail"]["parallel_efficiency"] = scaling.get(
        "parallel_efficiency", {}
    )
    return headline


if __name__ == "__main__":
    res = _bench()
    print(json.dumps(res))  # line 1 = the driver contract
    if os.environ.get("BENCH_CONFIGS", "") == "lines":
        for name, cfg in res["detail"]["configs"].items():
            print(json.dumps({"metric": name, **cfg}))
    # ... and AGAIN as the last line: the driver stores only the output
    # tail, so the artifact must be self-contained (VERDICT r5 #8 — the
    # r5 artifact lost its headline)
    print(json.dumps(res))
