"""Benchmark: transform() + groupby-agg rows/sec — jax engine vs native.

BASELINE.md headline: rows/sec/chip on a numeric transform()+groupby,
jax (device) vs NativeExecutionEngine (pandas). Prints ONE json line:
``{"metric":..., "value":..., "unit":..., "vs_baseline":...}`` where value is
the jax engine's rows/sec and vs_baseline its speedup over native.

Env knobs: BENCH_ROWS (default 100_000_000 per BASELINE.md north star /
capped 4_000_000 native, scaled to rows/sec), BENCH_GROUPS (default 1024).
"""

import json
import os
import time
from typing import Any, Dict


def _bench() -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pandas as pd

    from fugue_tpu import transform
    from fugue_tpu.column import col
    from fugue_tpu.column import functions as ff
    from fugue_tpu.execution import make_execution_engine
    from fugue_tpu.execution.api import aggregate

    n_rows = int(os.environ.get("BENCH_ROWS", 100_000_000))
    n_groups = int(os.environ.get("BENCH_GROUPS", 1024))
    n_native = min(n_rows, int(os.environ.get("BENCH_NATIVE_ROWS", 4_000_000)))

    rng = np.random.default_rng(42)
    # float32 + int32: TPU-friendly dtypes (f64 has no TPU hardware path)
    keys = rng.integers(0, n_groups, n_rows).astype(np.int32)
    values = rng.random(n_rows).astype(np.float32)

    # ---- native (pandas) baseline ---------------------------------------
    pdf_small = pd.DataFrame({"k": keys[:n_native], "v": values[:n_native]})

    def pandas_udf(df: pd.DataFrame) -> pd.DataFrame:
        return df.assign(v2=df["v"] * 2.0 + 1.0)

    native = make_execution_engine("native")
    t0 = time.perf_counter()
    out = transform(pdf_small, pandas_udf, schema="*,v2:float", engine=native,
                    as_fugue=True)
    agg = aggregate(
        out, partition_by="k",
        s=ff.sum(col("v2")), m=ff.avg(col("v2")), c=ff.count(col("v2")),
        engine=native, as_fugue=True,
    )
    agg.as_local()
    native_secs = time.perf_counter() - t0
    native_rps = n_native / native_secs

    # ---- jax engine (device) --------------------------------------------
    jdf_pd = pd.DataFrame({"k": keys, "v": values})
    engine = make_execution_engine("jax")

    def jax_udf(arrs: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        return {"k": arrs["k"], "v2": arrs["v"] * jnp.float32(2.0) + 1.0}

    src = engine.to_df(jdf_pd)  # device placement outside the timed region,
    # matching the reference measurement shape (data already in the engine)

    def run_once() -> float:
        t0 = time.perf_counter()
        out = transform(src, jax_udf, schema="k:int,v2:float", engine=engine,
                        as_fugue=True)
        agg = aggregate(
            out, partition_by="k",
            s=ff.sum(col("v2")), m=ff.avg(col("v2")), c=ff.count(col("v2")),
            engine=engine, as_fugue=True,
        )
        # materialize the (small) result to host — the honest endpoint,
        # same as the native path's as_local(); block_until_ready alone is
        # not trustworthy on relayed TPU backends. One async wave.
        arrs = [c.data for c in agg.native.columns.values() if c.on_device]
        if agg.native.row_valid is not None:  # type: ignore
            arrs.append(agg.native.row_valid)  # type: ignore
        jax.device_get(arrs)
        return time.perf_counter() - t0

    cold_secs = run_once()  # includes jit compilation at full shapes
    warm = sorted(run_once() for _ in range(5))
    jax_secs = warm[len(warm) // 2]  # median steady state
    jax_rps = n_rows / jax_secs

    return {
        "metric": "transform_groupby_rows_per_sec",
        "value": round(jax_rps, 1),
        "unit": "rows/sec",
        "vs_baseline": round(jax_rps / native_rps, 2),
        "detail": {
            "rows_jax": n_rows,
            "rows_native": n_native,
            "groups": n_groups,
            "jax_secs": round(jax_secs, 4),
            "jax_cold_secs": round(cold_secs, 4),
            "native_secs": round(native_secs, 4),
            "native_rows_per_sec": round(native_rps, 1),
            "devices": len(__import__("jax").devices()),
            "platform": __import__("jax").devices()[0].platform,
        },
    }


if __name__ == "__main__":
    print(json.dumps(_bench()))
